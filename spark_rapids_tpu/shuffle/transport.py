"""Multi-host shuffle transport: TCP transfer server + fetching client.

Reference mapping (SURVEY.md §2.8):
- ``RapidsShuffleServer.scala:67-671`` -> :class:`ShuffleServer` — serves
  metadata and streams table bytes through fixed-size send windows
  (``BufferSendState`` windowing -> CRC-tagged chunk frames).
- ``RapidsShuffleClient.scala:480-612`` -> :class:`ShuffleClient` — fetch
  protocol: MetadataRequest -> MetadataResponse -> TransferRequest(s) with
  inflight-byte throttling (``RapidsShuffleTransport.scala:413-435``),
  chunk reassembly, batch reconstruction.
- ``RapidsShuffleIterator.scala:49-365`` -> :meth:`ShuffleClient.fetch`'s
  retry loop — transport errors surface as :class:`ShuffleFetchError` after
  bounded retries (the reference throws RapidsShuffleFetchFailedException to
  trigger Spark's stage retry; standalone, the caller decides).

The UCX/RDMA plane of the reference maps to ICI collectives (parallel/mesh);
this TCP plane is the DCN fallback for inter-host fetches, stragglers, and
elastic retry, exactly the split SURVEY.md §5 calls for.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column
from . import wire
from .wire import (ERROR, META_REQ, META_RESP, XFER_CHUNK, XFER_DONE,
                   XFER_REQ, ArrayDesc, BufferDesc, FrameReader, encode_frame)


class ShuffleFetchError(RuntimeError):
    """Fetch failed after retries (RapidsShuffleFetchFailedException analog:
    the caller maps this to a stage retry / recompute)."""


# ---------------------------------------------------------------------------
# Server-side store
# ---------------------------------------------------------------------------

class ShuffleStore:
    """(shuffle_id, reduce_id) -> registered host buffers with metadata
    (ShuffleBufferCatalog analog, host-tier: the transfer server serves
    bytes from host staging, never touching the device)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._next_id = 1
        self._buffers: Dict[int, Tuple[BufferDesc, List[np.ndarray]]] = {}
        self._by_partition: Dict[Tuple[int, int], List[int]] = {}
        self._complete: set = set()

    def register_batch(self, shuffle_id: int, reduce_id: int,
                       batch: ColumnarBatch) -> int:
        arrays = [np.asarray(a) for c in batch.columns for a in c.arrays()]
        descs = [ArrayDesc(str(a.dtype), a.shape, a.nbytes) for a in arrays]
        with self._mu:
            bid = self._next_id
            self._next_id += 1
            desc = BufferDesc(
                bid, shuffle_id, reduce_id, batch.num_rows,
                [f.name for f in batch.schema],
                [f.dtype.name for f in batch.schema], descs)
            self._buffers[bid] = (desc, arrays)
            self._by_partition.setdefault((shuffle_id, reduce_id),
                                          []).append(bid)
        return bid

    def metas(self, shuffle_id: int, reduce_ids: List[int]
              ) -> List[BufferDesc]:
        with self._mu:
            out = []
            for rid in reduce_ids:
                for bid in self._by_partition.get((shuffle_id, rid), []):
                    out.append(self._buffers[bid][0])
            return out

    def payload(self, buffer_id: int) -> Tuple[BufferDesc, bytes]:
        with self._mu:
            desc, arrays = self._buffers[buffer_id]
        return desc, b"".join(a.tobytes() for a in arrays)

    def mark_complete(self, shuffle_id: int) -> None:
        """Map phase for this shuffle is finished: every slice is
        registered, remote fetches may proceed (the stage-scheduling
        ordering Spark provides; a flag replaces it standalone)."""
        with self._mu:
            self._complete.add(shuffle_id)

    def is_complete(self, shuffle_id: int) -> bool:
        with self._mu:
            return shuffle_id in self._complete

    def local_batches(self, shuffle_id: int, reduce_id: int
                      ) -> List[ColumnarBatch]:
        """Short-circuit read of locally-registered slices (the
        RapidsCachingReader local-block path — no socket, no copy of the
        payload bytes)."""
        with self._mu:
            pairs = [self._buffers[bid]
                     for bid in self._by_partition.get(
                         (shuffle_id, reduce_id), [])]
        out = []
        for desc, arrays in pairs:
            out.append(_rebuild_from_arrays(desc, arrays))
        return out

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._mu:
            gone = [k for k in self._by_partition if k[0] == shuffle_id]
            for k in gone:
                for bid in self._by_partition.pop(k):
                    self._buffers.pop(bid, None)
            self._complete.discard(shuffle_id)


# ---------------------------------------------------------------------------
# Connections (socket + in-process mock share this surface)
# ---------------------------------------------------------------------------

class Connection:
    """Byte-stream connection surface (ClientConnection/ServerConnection
    analog, RapidsShuffleTransport.scala:165-370)."""

    def send(self, data: bytes) -> None:
        raise NotImplementedError

    def read_exact(self, n: int) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SocketConnection(Connection):
    def __init__(self, sock: socket.socket):
        self.sock = sock

    def send(self, data: bytes) -> None:
        self.sock.sendall(data)

    def read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("peer closed")
            out += chunk
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class ShuffleServer:
    """Serves shuffle metadata + windowed buffer streams over TCP."""

    def __init__(self, store: ShuffleStore, host: str = "127.0.0.1",
                 port: int = 0, chunk_bytes: int = wire.DEFAULT_CHUNK_BYTES,
                 codec: str = "none"):
        from .compression import get_codec
        self.store = store
        self.chunk_bytes = chunk_bytes
        self.codec = get_codec(codec)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> "ShuffleServer":
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                sock, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self.handle_connection,
                                 args=(SocketConnection(sock),), daemon=True)
            t.start()
            self._threads.append(t)

    def handle_connection(self, conn: Connection) -> None:
        """One request/response session (the server handler loop,
        RapidsShuffleServer.scala:97-167). Public so the mock rig can drive
        it directly over an in-process connection."""
        reader = FrameReader(conn.read_exact)
        try:
            while True:
                msg_type, header, _payload = reader.next_frame()
                if msg_type == META_REQ:
                    sid = header["shuffle_id"]
                    metas = self.store.metas(sid, header["reduce_ids"])
                    conn.send(encode_frame(META_RESP, {
                        "buffers": [m.to_json() for m in metas],
                        "complete": self.store.is_complete(sid)}))
                elif msg_type == XFER_REQ:
                    self._send_buffers(conn, header["buffer_ids"])
                else:
                    conn.send(encode_frame(
                        ERROR, {"message": f"bad msg {msg_type}"}))
                    return
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def _send_buffers(self, conn: Connection, buffer_ids: List[int]) -> None:
        """Stream each buffer through fixed-size chunk windows
        (BufferSendState.next windowing)."""
        for bid in buffer_ids:
            try:
                desc, payload = self.store.payload(bid)
            except KeyError:
                conn.send(encode_frame(ERROR,
                                       {"message": f"unknown buffer {bid}"}))
                return
            ranges = wire.chunk_ranges(len(payload), self.chunk_bytes)
            for seq, (off, ln) in enumerate(ranges):
                raw = payload[off:off + ln]
                body = self.codec.compress(raw)
                conn.send(encode_frame(XFER_CHUNK, {
                    "buffer_id": bid, "seq": seq, "n_chunks": len(ranges),
                    "offset": off, "raw_len": ln,
                    "codec": self.codec.name,
                    "crc32": wire.chunk_crc(body)}, body))
        conn.send(encode_frame(XFER_DONE, {"buffer_ids": buffer_ids}))

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

class ShuffleClient:
    """Fetches shuffle partitions from a peer transfer server.

    Inflight throttling: transfer requests are issued so at most
    ``max_inflight_bytes`` of advertised buffer bytes are outstanding at a
    time (RapidsShuffleTransport throttle, :413-435) — a pull window that
    bounds receive-side memory no matter how large the partition is.
    Retries: each fetch attempt uses a fresh connection; CRC mismatches and
    connection failures retry up to ``max_retries`` with backoff.
    """

    def __init__(self, connect: Callable[[], Connection],
                 max_inflight_bytes: int = 8 << 20,
                 max_retries: int = 3, retry_backoff_s: float = 0.05,
                 bounce: Optional["BounceBufferManager"] = None):
        from ..exec.native_alloc import BounceBufferManager
        self._connect = connect
        self.max_inflight_bytes = max_inflight_bytes
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        # receive staging: chunk reassembly sub-allocates windows out of one
        # arena (BounceBufferManager.scala:35) instead of transient buffers
        self.bounce = bounce or BounceBufferManager(
            max(2 * max_inflight_bytes, 16 << 20))
        self.metrics: Dict[str, int] = {"retries": 0, "bytes_fetched": 0,
                                        "chunks": 0, "bounce_misses": 0}

    @staticmethod
    def for_address(host: str, port: int, **kw) -> "ShuffleClient":
        def connect():
            sock = socket.create_connection((host, port), timeout=10)
            return SocketConnection(sock)
        return ShuffleClient(connect, **kw)

    # -- public API ----------------------------------------------------------
    def fetch_when_complete(self, shuffle_id: int, reduce_ids: List[int],
                            timeout_s: float = 60.0,
                            poll_s: float = 0.05) -> List[ColumnarBatch]:
        """Fetch once the peer's map phase for ``shuffle_id`` is complete,
        polling its metadata endpoint with backoff (the standalone stand-in
        for Spark's stage-scheduling guarantee that map outputs exist
        before the reduce stage fetches them)."""
        deadline = time.monotonic() + timeout_s
        delay = poll_s
        while True:
            conn = None
            try:
                # the connect itself is the most likely transient failure
                # (backlog full / peer restarting): poll it too
                conn = self._connect()
                conn.send(encode_frame(META_REQ, {"shuffle_id": shuffle_id,
                                                  "reduce_ids": []}))
                reader = FrameReader(conn.read_exact)
                msg_type, header, _ = reader.next_frame()
                complete = msg_type == META_RESP and header.get("complete")
            except (ConnectionError, OSError):
                complete = False
            finally:
                if conn is not None:
                    conn.close()
            if complete:
                return self.fetch(shuffle_id, reduce_ids)
            if time.monotonic() > deadline:
                raise ShuffleFetchError(
                    f"peer map phase for shuffle {shuffle_id} not complete "
                    f"after {timeout_s}s")
            time.sleep(delay)
            delay = min(delay * 2, 1.0)

    def fetch(self, shuffle_id: int, reduce_ids: List[int]
              ) -> List[ColumnarBatch]:
        """Fetch all batches of the given reduce partitions (doFetch,
        RapidsShuffleClient.scala:480)."""
        last_err: Optional[Exception] = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                self.metrics["retries"] += 1
                time.sleep(self.retry_backoff_s * attempt)
            try:
                return self._fetch_once(shuffle_id, reduce_ids)
            except (ConnectionError, OSError, ValueError) as e:
                last_err = e
        raise ShuffleFetchError(
            f"shuffle {shuffle_id} partitions {reduce_ids} failed after "
            f"{self.max_retries + 1} attempts: {last_err}") from last_err

    # -- one attempt ---------------------------------------------------------
    def _fetch_once(self, shuffle_id: int, reduce_ids: List[int]
                    ) -> List[ColumnarBatch]:
        conn = self._connect()
        try:
            conn.send(encode_frame(META_REQ, {"shuffle_id": shuffle_id,
                                              "reduce_ids": reduce_ids}))
            reader = FrameReader(conn.read_exact)
            msg_type, header, _ = reader.next_frame()
            if msg_type == ERROR:
                raise ConnectionError(header.get("message", "server error"))
            assert msg_type == META_RESP, msg_type
            metas = [BufferDesc.from_json(d) for d in header["buffers"]]

            # pending transfer queue with inflight-byte throttling
            pending = list(metas)
            inflight: Dict[int, BufferDesc] = {}
            inflight_bytes = 0
            received: Dict[int, bytearray] = {}
            seen_chunks: Dict[int, int] = {}
            done: List[ColumnarBatch] = []

            def issue():
                nonlocal inflight_bytes
                batch_ids = []
                while pending and (
                        not inflight or
                        inflight_bytes + pending[0].total_bytes
                        <= self.max_inflight_bytes):
                    m = pending.pop(0)
                    inflight[m.buffer_id] = m
                    inflight_bytes += m.total_bytes
                    batch_ids.append(m.buffer_id)
                if batch_ids:
                    conn.send(encode_frame(XFER_REQ,
                                           {"buffer_ids": batch_ids}))

            issue()
            while inflight or pending:
                msg_type, header, payload = reader.next_frame()
                if msg_type == ERROR:
                    raise ConnectionError(header.get("message"))
                if msg_type == XFER_DONE:
                    continue
                assert msg_type == XFER_CHUNK, msg_type
                bid = header["buffer_id"]
                if wire.chunk_crc(payload) != header["crc32"]:
                    raise ValueError(f"chunk crc mismatch for buffer {bid}")
                codec_name = header.get("codec", "none")
                if codec_name != "none":
                    from .compression import get_codec
                    payload = get_codec(codec_name).decompress(
                        payload, header.get("raw_len", 0))
                buf = received.get(bid)
                if buf is None:
                    total = inflight[bid].total_bytes
                    buf = self.bounce.acquire(total)
                    if buf is None:              # arena exhausted: fall back
                        self.metrics["bounce_misses"] += 1
                        buf = bytearray(total)
                    received[bid] = buf
                buf[header["offset"]:header["offset"] + len(payload)] = \
                    payload
                self.metrics["chunks"] += 1
                seen_chunks[bid] = seen_chunks.get(bid, 0) + 1
                if seen_chunks[bid] == header["n_chunks"]:
                    m = inflight.pop(bid)
                    inflight_bytes -= m.total_bytes
                    self.metrics["bytes_fetched"] += m.total_bytes
                    buf = received.pop(bid)
                    done.append(_rebuild_batch(m, bytes(buf)))
                    if isinstance(buf, memoryview):
                        self.bounce.release(buf)
                    issue()
            return done
        finally:
            conn.close()


def _rebuild_batch(meta: BufferDesc, payload: bytes) -> ColumnarBatch:
    """Reconstruct a ColumnarBatch from wire bytes (getBatchFromMeta,
    MetaUtils.scala:33-241)."""
    arrays: List[np.ndarray] = []
    off = 0
    for d in meta.arrays:
        a = np.frombuffer(payload, dtype=np.dtype(d.dtype),
                          count=d.nbytes // np.dtype(d.dtype).itemsize,
                          offset=off).reshape(d.shape)
        arrays.append(a)
        off += d.nbytes
    return _rebuild_from_arrays(meta, arrays)


def _rebuild_from_arrays(meta: BufferDesc,
                         arrays: List[np.ndarray]) -> ColumnarBatch:
    """Host arrays + metadata -> device batch (shared by the wire path and
    the local short-circuit read)."""
    fields = [dt.Field(n, dt.of(t))
              for n, t in zip(meta.field_names, meta.field_dtypes)]
    schema = dt.Schema(fields)
    import jax.numpy as jnp
    cols: List[Column] = []
    i = 0
    for f in fields:
        if f.dtype.var_width:
            cols.append(Column(f.dtype, jnp.asarray(arrays[i]),
                               jnp.asarray(arrays[i + 1]),
                               jnp.asarray(arrays[i + 2])))
            i += 3
        else:
            cols.append(Column(f.dtype, jnp.asarray(arrays[i]),
                               jnp.asarray(arrays[i + 1])))
            i += 2
    return ColumnarBatch(schema, cols, meta.num_rows)
