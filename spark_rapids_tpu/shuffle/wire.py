"""Shuffle wire format: the control/data plane framing for the DCN (TCP)
transfer server.

Reference: the flatbuffer messages of ``sql-plugin/src/main/format/*.fbs``
(TableMeta / MetadataRequest / MetadataResponse / TransferRequest) driven by
``RapidsShuffleClient.scala:376-737`` and ``RapidsShuffleServer.scala:67-671``.
TPU-standalone design: the control plane is length-prefixed JSON (the role
flatbuffers plays — small, structural, versioned), the data plane is raw
array bytes in fixed-size CRC-tagged chunks (the bounce-buffer windows of
``WindowedBlockIterator``/``BufferSendState``, moved from RDMA registration
windows to TCP frames).

Frame layout (all little-endian):
    u32 total_len | u8 msg_type | u32 header_len | header(JSON) | payload
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# message types
META_REQ = 1       # {shuffle_id, reduce_ids[], fingerprint?}
META_RESP = 2      # {buffers: [BufferDesc...]}
XFER_REQ = 3       # {buffer_ids[]}
XFER_CHUNK = 4     # {buffer_id, seq, n_chunks, offset, crc32} + payload
XFER_DONE = 5      # {buffer_ids[], bytes_sent, chunks_sent} — the server's
                   # send-window totals for this transfer (the client may
                   # cross-check its reassembly; older peers omit them)
ERROR = 6          # {message, code?}  code in {"desync", "released"}
RELEASE = 7        # {shuffle_id, worker_id} — reduce-side done-reading ack

_HDR = struct.Struct("<IBI")

# data-plane chunk size: the bounce-buffer window (BounceBufferManager's
# fixed-size buffers; 1 MiB keeps per-frame latency low on DCN)
DEFAULT_CHUNK_BYTES = 1 << 20


@dataclass
class ArrayDesc:
    """One device array of a columnar batch (TableMeta ColumnMeta analog)."""
    dtype: str
    shape: Tuple[int, ...]
    nbytes: int

    def to_json(self):
        return {"dtype": self.dtype, "shape": list(self.shape),
                "nbytes": self.nbytes}

    @staticmethod
    def from_json(d):
        return ArrayDesc(d["dtype"], tuple(d["shape"]), d["nbytes"])


@dataclass
class BufferDesc:
    """Shuffle buffer metadata (TableMeta analog): enough to reconstruct a
    ColumnarBatch from raw bytes on the receiving side."""
    buffer_id: int
    shuffle_id: int
    reduce_id: int
    num_rows: int
    field_names: List[str]
    field_dtypes: List[str]        # columnar dtype names
    arrays: List[ArrayDesc] = field(default_factory=list)

    @property
    def total_bytes(self) -> int:
        return sum(a.nbytes for a in self.arrays)

    def to_json(self):
        return {"buffer_id": self.buffer_id, "shuffle_id": self.shuffle_id,
                "reduce_id": self.reduce_id, "num_rows": self.num_rows,
                "field_names": self.field_names,
                "field_dtypes": self.field_dtypes,
                "arrays": [a.to_json() for a in self.arrays]}

    @staticmethod
    def from_json(d):
        return BufferDesc(
            d["buffer_id"], d["shuffle_id"], d["reduce_id"], d["num_rows"],
            list(d["field_names"]), list(d["field_dtypes"]),
            [ArrayDesc.from_json(a) for a in d["arrays"]])


def encode_frame(msg_type: int, header: Dict[str, Any],
                 payload: bytes = b"") -> bytes:
    h = json.dumps(header).encode()
    total = _HDR.size + len(h) + len(payload)
    return _HDR.pack(total, msg_type, len(h)) + h + payload


class FrameReader:
    """Incremental frame decoder over a read(n)->bytes callable."""

    def __init__(self, read_exact):
        self._read = read_exact

    def next_frame(self) -> Tuple[int, Dict[str, Any], bytes]:
        head = self._read(_HDR.size)
        total, msg_type, hlen = _HDR.unpack(head)
        rest = self._read(total - _HDR.size)
        header = json.loads(rest[:hlen].decode())
        return msg_type, header, rest[hlen:]


def chunk_ranges(total_bytes: int, chunk_bytes: int
                 ) -> List[Tuple[int, int]]:
    """(offset, length) windows covering [0, total_bytes) — the
    WindowedBlockIterator math (WindowedBlockIterator.scala), collapsed to
    one flat buffer per shuffle table."""
    if total_bytes == 0:
        return [(0, 0)]
    out = []
    off = 0
    while off < total_bytes:
        ln = min(chunk_bytes, total_bytes - off)
        out.append((off, ln))
        off += ln
    return out


def chunk_crc(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF
