"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's approach of testing distributed behavior without a real
cluster (SURVEY.md §4: local-mode + mocks, never multi-node in CI). The env vars
MUST be set before jax initializes its backends, so this module sets them at
import time (pytest imports conftest before any test module imports jax).
"""

import os

# FORCE cpu (not setdefault): the CI/axon environment pre-sets JAX_PLATFORMS
# to the real TPU, where float64 is emulated and loses ULPs — unit tests
# validate semantics on the virtual CPU mesh (SURVEY.md §4 implication (e));
# the bench runs on the real chip. The axon sitecustomize registers the TPU
# backend regardless of env, so ALSO pin jax.config below.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# lockdep runs in `record` mode throughout the test suite (the conf's
# documented tests/bench default): every session bootstrap primes the
# mode from its conf, and the env override reaches every TpuConf built
# without an explicit setting. Tests that need `enforce` (or `off`) set
# the key on their own session and restore after. Measured cost: ~0 on
# compile-dominated files, ~0.5s on the most lock-heavy file — suite
# wall time is unaffected at the tier-1 gate's resolution.
os.environ.setdefault(
    "SPARK_RAPIDS_TPU_CONF__SPARK__RAPIDS__TPU__SQL__ANALYSIS__LOCKDEP",
    "record")

# buffer-lifecycle ledger rides the suite in `record` mode (same
# discipline as lockdep above): leaks and dead-buffer accesses are
# counted + flight-recorded, never raised. Tests that exercise
# `enforce` install it explicitly and reset after.
os.environ.setdefault(
    "SPARK_RAPIDS_TPU_CONF__SPARK__RAPIDS__TPU__SQL__ANALYSIS"
    "__BUFFERLEDGER",
    "record")

# tests drive bench/dryrun code paths (test_partitioning runs the full
# multichip dryrun): their regression-gate stamps must land in a scratch
# history file, never in the committed benchmarks/reports JSONL
os.environ.setdefault("SPARK_RAPIDS_TPU_BENCH_HISTORY",
                      "/tmp/spark_rapids_tpu_test_history.jsonl")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` gate")


@pytest.fixture(scope="session")
def devices():
    import jax
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from jax.sharding import Mesh
    import numpy as np
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ("workers",))
