"""Golden-compare harness: run the same query on the CPU (pandas) engine and
the TPU engine and diff results.

Direct analog of the reference's core correctness strategy
(SparkQueryCompareTestSuite.withCpuSparkSession/withGpuSparkSession,
tests/.../SparkQueryCompareTestSuite.scala:153-161,314-363; pytest side
asserts.assert_gpu_and_cpu_are_equal_collect, integration_tests asserts.py).
"""

from __future__ import annotations

import math
from typing import Any, Callable, List, Optional

from spark_rapids_tpu.api.session import TpuSession


def _norm_cell(v: Any) -> Any:
    import numpy as np
    if v is None:
        return None
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    try:
        import pandas as pd
        if v is pd.NaT or v is pd.NA:
            return None
    except Exception:
        pass
    return v


def _sort_key(row):
    return tuple(
        (v is None,
         "nan" if isinstance(v, float) and math.isnan(v) else
         (repr(v) if not isinstance(v, (int, float, bool)) else ""),
         v if isinstance(v, (int, float)) and not (
             isinstance(v, float) and math.isnan(v)) else 0)
        for v in row)


def _compare_rows(cpu_rows: List[tuple], tpu_rows: List[tuple],
                  approx: Optional[float], ignore_order: bool) -> None:
    assert len(cpu_rows) == len(tpu_rows), (
        f"row count mismatch: cpu={len(cpu_rows)} tpu={len(tpu_rows)}\n"
        f"cpu: {cpu_rows[:10]}\ntpu: {tpu_rows[:10]}")
    if ignore_order:
        cpu_rows = sorted(cpu_rows, key=_sort_key)
        tpu_rows = sorted(tpu_rows, key=_sort_key)
    for ri, (cr, tr) in enumerate(zip(cpu_rows, tpu_rows)):
        assert len(cr) == len(tr), f"row {ri}: arity {len(cr)} vs {len(tr)}"
        for ci, (cv, tv) in enumerate(zip(cr, tr)):
            cv, tv = _norm_cell(cv), _norm_cell(tv)
            if cv is None or tv is None:
                assert cv is None and tv is None, \
                    f"row {ri} col {ci}: cpu={cv!r} tpu={tv!r}"
                continue
            if isinstance(cv, float) and isinstance(tv, float):
                if math.isnan(cv) or math.isnan(tv):
                    assert math.isnan(cv) and math.isnan(tv), \
                        f"row {ri} col {ci}: cpu={cv!r} tpu={tv!r}"
                    continue
                if approx is not None:
                    tol = approx * max(abs(cv), abs(tv), 1e-300)
                    assert abs(cv - tv) <= max(tol, 1e-12), \
                        f"row {ri} col {ci}: cpu={cv!r} tpu={tv!r}"
                    continue
            assert cv == tv or (isinstance(cv, float) and cv == tv), \
                f"row {ri} col {ci}: cpu={cv!r} tpu={tv!r}"


def assert_tpu_and_cpu_equal(build_df: Callable[[TpuSession], Any],
                             approx: Optional[float] = None,
                             ignore_order: bool = True,
                             conf: Optional[dict] = None,
                             expect_fallback: Optional[List[str]] = None):
    """Run ``build_df(session)`` twice — once forced through the CPU engine,
    once on the TPU engine — and compare collected rows."""
    settings = {"spark.rapids.tpu.sql.explain": "NONE"}
    settings.update(conf or {})
    session = TpuSession.builder.config(dict(settings)).getOrCreate()

    # CPU run: execute the logical plan directly on the pandas engine
    df = build_df(session)
    from spark_rapids_tpu.cpu.engine import execute as cpu_execute
    cpu_df = cpu_execute(df._analyzed())
    cpu_rows = [tuple(r) for r in cpu_df.itertuples(index=False, name=None)]

    # TPU run
    tpu_rows = df.collect()
    if expect_fallback is None:
        session.assert_on_tpu(allowed_fallbacks=())
    else:
        session.assert_on_tpu(allowed_fallbacks=expect_fallback)
    _compare_rows(cpu_rows, tpu_rows, approx, ignore_order)
    return tpu_rows
