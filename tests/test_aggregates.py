"""Group-by / reduction kernel tests against pandas-style oracles.

Reference analog: HashAggregatesSuite (SURVEY.md §4 ring 1).
"""

import math

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.ops.aggregates import (AggSpec, groupby_aggregate,
                                             reduce_aggregate)


def _col(vals, dtype):
    return Column.from_pylist(vals, dtype)


def _run_groupby(keys, specs, n):
    cap = keys[0].capacity
    out_keys, out_aggs, n_groups = groupby_aggregate(keys, specs, n, cap)
    g = int(n_groups)
    return ([k.to_pylist(g) for k in out_keys],
            [a.to_pylist(g) for a in out_aggs])


def test_groupby_sum_count():
    k = _col([1, 2, 1, 2, 1, None], dt.INT64)
    v = _col([10, 20, 30, None, 50, 60], dt.INT64)
    keys, aggs = _run_groupby(
        [k], [AggSpec("sum", v), AggSpec("count", v), AggSpec("count_star", None)], 6)
    # groups sorted: NULL first, then 1, 2
    assert keys[0] == [None, 1, 2]
    assert aggs[0] == [60, 90, 20]
    assert aggs[1] == [1, 3, 1]
    assert aggs[2] == [1, 3, 2]


def test_groupby_min_max_avg():
    k = _col(["a", "b", "a", "b"], dt.STRING)
    v = _col([3.0, None, 1.0, 7.5], dt.FLOAT64)
    keys, aggs = _run_groupby(
        [k], [AggSpec("min", v), AggSpec("max", v), AggSpec("avg", v)], 4)
    assert keys[0] == ["a", "b"]
    assert aggs[0] == [1.0, 7.5]
    assert aggs[1] == [3.0, 7.5]
    assert aggs[2] == [2.0, 7.5]


def test_groupby_all_null_group():
    k = _col([1, 1, 2], dt.INT32)
    v = _col([None, None, 5], dt.INT64)
    keys, aggs = _run_groupby(
        [k], [AggSpec("sum", v), AggSpec("count", v), AggSpec("min", v)], 3)
    assert keys[0] == [1, 2]
    assert aggs[0] == [None, 5]
    assert aggs[1] == [0, 1]
    assert aggs[2] == [None, 5]


def test_groupby_string_minmax():
    k = _col([1, 1, 1], dt.INT32)
    v = _col(["pear", "apple", None], dt.STRING)
    keys, aggs = _run_groupby([k], [AggSpec("min", v), AggSpec("max", v)], 3)
    assert aggs[0] == ["apple"]
    assert aggs[1] == ["pear"]


def test_groupby_float_nan():
    nan = float("nan")
    k = _col([1, 1, 2, 2], dt.INT32)
    v = _col([nan, 2.0, 3.0, 4.0], dt.FLOAT64)
    keys, aggs = _run_groupby([k], [AggSpec("min", v), AggSpec("max", v)], 4)
    assert aggs[0][0] == 2.0          # min skips NaN (NaN is largest)
    assert math.isnan(aggs[1][0])     # max of group with NaN = NaN
    assert aggs[0][1] == 3.0 and aggs[1][1] == 4.0


def test_groupby_first_last():
    k = _col([1, 1, 1, 2], dt.INT32)
    v = _col([None, 20, 30, 40], dt.INT64)
    keys, aggs = _run_groupby(
        [k], [AggSpec("first", v, ignore_nulls=True),
              AggSpec("first", v, ignore_nulls=False),
              AggSpec("last", v)], 4)
    assert aggs[0] == [20, 40]
    assert aggs[1] == [None, 40]
    assert aggs[2] == [30, 40]


def test_groupby_multi_key():
    k1 = _col([1, 1, 2, 1], dt.INT32)
    k2 = _col(["x", "y", "x", "x"], dt.STRING)
    v = _col([1, 2, 3, 4], dt.INT64)
    keys, aggs = _run_groupby([k1, k2], [AggSpec("sum", v)], 4)
    assert keys[0] == [1, 1, 2]
    assert keys[1] == ["x", "y", "x"]
    assert aggs[0] == [5, 2, 3]


def test_groupby_bool_minmax():
    k = _col([1, 1, 2], dt.INT32)
    v = _col([True, False, True], dt.BOOL)
    keys, aggs = _run_groupby([k], [AggSpec("min", v), AggSpec("max", v)], 3)
    assert aggs[0] == [False, True]
    assert aggs[1] == [True, True]


def test_reduce_no_groups():
    v = _col([1, 2, None, 4], dt.INT64)
    out = reduce_aggregate(
        [AggSpec("sum", v), AggSpec("count", v), AggSpec("avg", v),
         AggSpec("min", v), AggSpec("max", v)], 4, v.capacity)
    assert [c.to_pylist(1)[0] for c in out] == [7, 3, 7 / 3, 1, 4]


def test_reduce_empty_input():
    v = Column.full_null(dt.INT64, 128)
    out = reduce_aggregate(
        [AggSpec("sum", v), AggSpec("count", v), AggSpec("count_star", None)],
        0, 128)
    assert out[0].to_pylist(1) == [None]
    assert out[1].to_pylist(1) == [0]
    assert out[2].to_pylist(1) == [0]


def test_groupby_large_random_vs_pandas():
    import pandas as pd
    rng = np.random.default_rng(42)
    n = 1000
    k = rng.integers(0, 50, n)
    v = rng.normal(size=n)
    null_mask = rng.random(n) < 0.1
    kcol = _col(list(k), dt.INT64)
    vcol = Column.from_pylist(
        [None if m else float(x) for m, x in zip(null_mask, v)], dt.FLOAT64)
    keys, aggs = _run_groupby(
        [kcol], [AggSpec("sum", vcol), AggSpec("count", vcol),
                 AggSpec("min", vcol), AggSpec("max", vcol)], n)
    df = pd.DataFrame({"k": k, "v": [None if m else x for m, x in zip(null_mask, v)]})
    g = df.groupby("k")["v"]
    expected = g.agg(["sum", "count", "min", "max"]).reset_index()
    assert keys[0] == list(expected["k"])
    # float sum order differs from pandas (the reference gates this behind
    # spark.rapids.sql.variableFloatAgg.enabled) — epsilon compare
    np.testing.assert_allclose(aggs[0], expected["sum"], rtol=1e-9)
    assert aggs[1] == list(expected["count"])
    np.testing.assert_allclose(aggs[2], expected["min"])
    np.testing.assert_allclose(aggs[3], expected["max"])
