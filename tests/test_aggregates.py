"""Group-by / reduction kernel tests against pandas-style oracles.

Reference analog: HashAggregatesSuite (SURVEY.md §4 ring 1).
"""

import math

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.ops.aggregates import (AggSpec, groupby_aggregate,
                                             reduce_aggregate)


def _col(vals, dtype):
    return Column.from_pylist(vals, dtype)


def _run_groupby(keys, specs, n):
    cap = keys[0].capacity
    out_keys, out_aggs, n_groups = groupby_aggregate(keys, specs, n, cap)
    g = int(n_groups)
    return ([k.to_pylist(g) for k in out_keys],
            [a.to_pylist(g) for a in out_aggs])


def test_groupby_sum_count():
    k = _col([1, 2, 1, 2, 1, None], dt.INT64)
    v = _col([10, 20, 30, None, 50, 60], dt.INT64)
    keys, aggs = _run_groupby(
        [k], [AggSpec("sum", v), AggSpec("count", v), AggSpec("count_star", None)], 6)
    # groups sorted: NULL first, then 1, 2
    assert keys[0] == [None, 1, 2]
    assert aggs[0] == [60, 90, 20]
    assert aggs[1] == [1, 3, 1]
    assert aggs[2] == [1, 3, 2]


def test_groupby_min_max_avg():
    k = _col(["a", "b", "a", "b"], dt.STRING)
    v = _col([3.0, None, 1.0, 7.5], dt.FLOAT64)
    keys, aggs = _run_groupby(
        [k], [AggSpec("min", v), AggSpec("max", v), AggSpec("avg", v)], 4)
    assert keys[0] == ["a", "b"]
    assert aggs[0] == [1.0, 7.5]
    assert aggs[1] == [3.0, 7.5]
    assert aggs[2] == [2.0, 7.5]


def test_groupby_all_null_group():
    k = _col([1, 1, 2], dt.INT32)
    v = _col([None, None, 5], dt.INT64)
    keys, aggs = _run_groupby(
        [k], [AggSpec("sum", v), AggSpec("count", v), AggSpec("min", v)], 3)
    assert keys[0] == [1, 2]
    assert aggs[0] == [None, 5]
    assert aggs[1] == [0, 1]
    assert aggs[2] == [None, 5]


def test_groupby_string_minmax():
    k = _col([1, 1, 1], dt.INT32)
    v = _col(["pear", "apple", None], dt.STRING)
    keys, aggs = _run_groupby([k], [AggSpec("min", v), AggSpec("max", v)], 3)
    assert aggs[0] == ["apple"]
    assert aggs[1] == ["pear"]


def test_groupby_float_nan():
    nan = float("nan")
    k = _col([1, 1, 2, 2], dt.INT32)
    v = _col([nan, 2.0, 3.0, 4.0], dt.FLOAT64)
    keys, aggs = _run_groupby([k], [AggSpec("min", v), AggSpec("max", v)], 4)
    assert aggs[0][0] == 2.0          # min skips NaN (NaN is largest)
    assert math.isnan(aggs[1][0])     # max of group with NaN = NaN
    assert aggs[0][1] == 3.0 and aggs[1][1] == 4.0


def test_groupby_first_last():
    k = _col([1, 1, 1, 2], dt.INT32)
    v = _col([None, 20, 30, 40], dt.INT64)
    keys, aggs = _run_groupby(
        [k], [AggSpec("first", v, ignore_nulls=True),
              AggSpec("first", v, ignore_nulls=False),
              AggSpec("last", v)], 4)
    assert aggs[0] == [20, 40]
    assert aggs[1] == [None, 40]
    assert aggs[2] == [30, 40]


def test_groupby_multi_key():
    k1 = _col([1, 1, 2, 1], dt.INT32)
    k2 = _col(["x", "y", "x", "x"], dt.STRING)
    v = _col([1, 2, 3, 4], dt.INT64)
    keys, aggs = _run_groupby([k1, k2], [AggSpec("sum", v)], 4)
    assert keys[0] == [1, 1, 2]
    assert keys[1] == ["x", "y", "x"]
    assert aggs[0] == [5, 2, 3]


def test_groupby_bool_minmax():
    k = _col([1, 1, 2], dt.INT32)
    v = _col([True, False, True], dt.BOOL)
    keys, aggs = _run_groupby([k], [AggSpec("min", v), AggSpec("max", v)], 3)
    assert aggs[0] == [False, True]
    assert aggs[1] == [True, True]


def test_reduce_no_groups():
    v = _col([1, 2, None, 4], dt.INT64)
    out = reduce_aggregate(
        [AggSpec("sum", v), AggSpec("count", v), AggSpec("avg", v),
         AggSpec("min", v), AggSpec("max", v)], 4, v.capacity)
    assert [c.to_pylist(1)[0] for c in out] == [7, 3, 7 / 3, 1, 4]


def test_reduce_empty_input():
    v = Column.full_null(dt.INT64, 128)
    out = reduce_aggregate(
        [AggSpec("sum", v), AggSpec("count", v), AggSpec("count_star", None)],
        0, 128)
    assert out[0].to_pylist(1) == [None]
    assert out[1].to_pylist(1) == [0]
    assert out[2].to_pylist(1) == [0]


def test_groupby_large_random_vs_pandas():
    import pandas as pd
    rng = np.random.default_rng(42)
    n = 1000
    k = rng.integers(0, 50, n)
    v = rng.normal(size=n)
    null_mask = rng.random(n) < 0.1
    kcol = _col(list(k), dt.INT64)
    vcol = Column.from_pylist(
        [None if m else float(x) for m, x in zip(null_mask, v)], dt.FLOAT64)
    keys, aggs = _run_groupby(
        [kcol], [AggSpec("sum", vcol), AggSpec("count", vcol),
                 AggSpec("min", vcol), AggSpec("max", vcol)], n)
    df = pd.DataFrame({"k": k, "v": [None if m else x for m, x in zip(null_mask, v)]})
    g = df.groupby("k")["v"]
    expected = g.agg(["sum", "count", "min", "max"]).reset_index()
    assert keys[0] == list(expected["k"])
    # float sum order differs from pandas (the reference gates this behind
    # spark.rapids.sql.variableFloatAgg.enabled) — epsilon compare
    np.testing.assert_allclose(aggs[0], expected["sum"], rtol=1e-9)
    assert aggs[1] == list(expected["count"])
    np.testing.assert_allclose(aggs[2], expected["min"])
    np.testing.assert_allclose(aggs[3], expected["max"])


# ---------------------------------------------------------------------------
# Dense-range MXU group-by
# ---------------------------------------------------------------------------

from spark_rapids_tpu.ops.aggregates import (  # noqa: E402
    dense_key_stats, groupby_aggregate_fast, groupby_dense)


def _run_dense(key, specs, n, extra_mask=None):
    rmin, decision = dense_key_stats(key, n, extra_mask)
    span = int(np.asarray(decision)[0])
    from spark_rapids_tpu.columnar.column import bucket
    Kb = bucket(span + 2, 128)
    out_keys, out_aggs, ng = groupby_dense(key, specs, n, Kb, rmin,
                                           extra_mask=extra_mask)
    g = int(ng)
    return ([k.to_pylist(g) for k in out_keys],
            [a.to_pylist(g) for a in out_aggs])


def test_dense_groupby_matches_sort_path():
    rng = np.random.default_rng(11)
    n = 500
    kv = [None if rng.random() < 0.08 else int(x)
          for x in rng.integers(-40, 40, n)]
    vv = [None if rng.random() < 0.1 else float(x)
          for x in rng.normal(0, 10, n)]
    k = _col(kv, dt.INT64)
    v = _col(vv, dt.FLOAT64)
    iv = _col([None if x is None else int(x * 7) for x in kv], dt.INT64)
    specs = [AggSpec("sum", v), AggSpec("count", v), AggSpec("avg", v),
             AggSpec("min", v), AggSpec("max", v), AggSpec("count_star", None),
             AggSpec("sum", iv), AggSpec("first", v), AggSpec("last", v)]
    dk, da = _run_dense(k, specs, n)
    sk, sa = _run_groupby([k], specs, n)
    # dense output: keys ascending with NULL group LAST; sort path: NULL first
    if sk[0] and sk[0][0] is None:
        sk = [col[1:] + col[:1] for col in sk]
        sa = [col[1:] + col[:1] for col in sa]
    assert dk[0] == sk[0]
    for i, (got, exp) in enumerate(zip(da, sa)):
        for a, b in zip(got, exp):
            if isinstance(a, float) and isinstance(b, float):
                # float sums ride f32 hi/lo + f64 chunk accumulation:
                # ~1e-6 abs per-chunk rounding (reference epsilon is 1e-4)
                assert a == pytest.approx(b, rel=2e-6, abs=2e-6), (i, a, b)
            else:
                assert a == b, (i, specs[i].op, got, exp)


def test_dense_int64_sum_bit_exact():
    big = 3_000_000_000_000_000_000
    k = _col([5, 5, 6, 6], dt.INT64)
    v = _col([big, big, -big, 17], dt.INT64)
    keys, aggs = _run_dense(k, [AggSpec("sum", v)], 4)
    assert keys[0] == [5, 6]
    # 2*big overflows int64 and must wrap exactly like Spark bigint
    import numpy as _np
    exp0 = int(_np.int64(_np.uint64(big * 2 % (1 << 64))))
    assert aggs[0] == [exp0, -big + 17]


def test_dense_negative_keys_and_null_group():
    k = _col([-3, -1, None, -3], dt.INT32)
    v = _col([1.0, 2.0, 3.0, 4.0], dt.FLOAT64)
    keys, aggs = _run_dense(k, [AggSpec("sum", v)], 4)
    assert keys[0] == [-3, -1, None]
    assert aggs[0] == [5.0, 2.0, 3.0]


def test_dense_extra_mask_filter_fold():
    k = _col([1, 2, 1, 2], dt.INT64)
    v = _col([10.0, 20.0, 30.0, 40.0], dt.FLOAT64)
    import jax.numpy as jnp
    mask = jnp.asarray([True, False, True, False] + [False] * (k.capacity - 4))
    keys, aggs = _run_dense(k, [AggSpec("sum", v)], 4, extra_mask=mask)
    assert keys[0] == [1]
    assert aggs[0] == [40.0]


def test_dense_all_null_keys():
    k = _col([None, None], dt.INT64)
    v = _col([1.0, 2.0], dt.FLOAT64)
    keys, aggs = _run_dense(k, [AggSpec("sum", v)], 2)
    assert keys[0] == [None]
    assert aggs[0] == [3.0]


def test_dense_empty_input():
    k = _col([], dt.INT64)
    v = _col([], dt.FLOAT64)
    keys, aggs = _run_dense(k, [AggSpec("sum", v)], 0)
    assert keys[0] == []
    assert aggs[0] == []


def test_groupby_fast_dispatches_dense_and_matches():
    """groupby_aggregate_fast with a dense int key must agree with the
    explicitly non-matmul sort path on random data."""
    rng = np.random.default_rng(23)
    n = 800
    kv = [None if rng.random() < 0.05 else int(x)
          for x in rng.integers(0, 200, n)]
    vv = [None if rng.random() < 0.1 else float(x)
          for x in rng.normal(0, 100, n)]
    k = _col(kv, dt.INT64)
    v = _col(vv, dt.FLOAT64)
    specs = [AggSpec("sum", v), AggSpec("avg", v), AggSpec("count", v),
             AggSpec("min", v), AggSpec("max", v)]
    cap = k.capacity
    fk, fa, fn = groupby_aggregate_fast([k], specs, n, cap, allow_matmul=True)
    gk, ga, gn = groupby_aggregate_fast([k], specs, n, cap, allow_matmul=False)
    assert fn == gn
    fkeys = fk[0].to_pylist(fn)
    gkeys = gk[0].to_pylist(gn)
    fmap = {kk: tuple(a.to_pylist(fn)[i] for a in fa)
            for i, kk in enumerate(fkeys)}
    gmap = {kk: tuple(a.to_pylist(gn)[i] for a in ga)
            for i, kk in enumerate(gkeys)}
    assert set(fmap) == set(gmap)
    for kk in fmap:
        for a, b in zip(fmap[kk], gmap[kk]):
            if isinstance(a, float) and isinstance(b, float):
                assert a == pytest.approx(b, rel=2e-6, abs=2e-6)
            else:
                assert a == b


def test_dense_dispatch_falls_back_on_f32_unsafe_floats():
    """Values beyond the f32-safe range (or inf) must not ride the hi/lo
    matmul split; the dispatch falls back to the exact f64 sort path."""
    k = _col([1, 1, 2, 2], dt.INT64)
    v = _col([1e40, 3.0, float("inf"), 5.0], dt.FLOAT64)
    fk, fa, fn = groupby_aggregate_fast([k], [AggSpec("sum", v)],
                                        4, k.capacity, allow_matmul=True)
    keys = fk[0].to_pylist(fn)
    sums = fa[0].to_pylist(fn)
    got = dict(zip(keys, sums))
    assert got[1] == 1e40 + 3.0
    assert got[2] == float("inf")


def test_dense_nan_poisons_only_its_group():
    """A NaN value must make only ITS group's sum/avg NaN, not every group."""
    nan = float("nan")
    k = _col([1, 1, 2, 2], dt.INT64)
    v = _col([nan, 2.0, 3.0, 4.0], dt.FLOAT64)
    keys, aggs = _run_dense(k, [AggSpec("sum", v), AggSpec("avg", v)], 4)
    assert keys[0] == [1, 2]
    assert math.isnan(aggs[0][0]) and math.isnan(aggs[1][0])
    assert aggs[0][1] == 7.0 and aggs[1][1] == 3.5


def test_fused_staged_matmul_groupby_matches_exact():
    """Force the MXU matmul segment path (off by default on CPU): the staged
    probe+kernel fused sort group-by must match the exact path to float-agg
    tolerance."""
    import numpy as np
    import pandas as pd
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    rng = np.random.default_rng(41)
    n = 5000
    df = pd.DataFrame({
        "k": [f"g{int(x)}" for x in rng.integers(0, 23, n)],  # string keys
        "v": rng.normal(0, 10, n),
        "q": rng.integers(0, 50, n)})
    s = TpuSession.builder.config({
        "spark.rapids.tpu.sql.explain": "NONE",
        "spark.rapids.tpu.sql.agg.matmul.enabled": "true"}).getOrCreate()
    got = {r[0]: r[1:] for r in
           (s.createDataFrame(df).filter(F.col("v") > -5)
            .groupBy("k").agg(F.sum("v").alias("sv"),
                              F.count("*").alias("n"),
                              F.avg("v").alias("av"),
                              F.sum("q").alias("sq"),
                              F.min("v").alias("mv")).collect())}
    sub = df[df.v > -5]
    exp = sub.groupby("k").agg(sv=("v", "sum"), n=("v", "size"),
                               av=("v", "mean"), sq=("q", "sum"),
                               mv=("v", "min"))
    assert len(got) == len(exp)
    for k, row in exp.iterrows():
        sv, cnt, av, sq, mv = got[k]
        assert cnt == row["n"] and sq == row["sq"]
        assert abs(sv - row["sv"]) <= 1e-6 * max(1, abs(row["sv"]))
        assert abs(av - row["av"]) <= 1e-6 * max(1, abs(row["av"]))
        assert abs(mv - row["mv"]) <= 1e-12
