"""Adaptive query execution (ISSUE 16, plan/aqe.py, docs/aqe.md).

Per-rule units — coalesce grouping, skew-split bounds (including the
ICI-plane prior-stats fallback), join promote/demote hysteresis (a
borderline build must not flap), drift feedback improving a repeat
plan's estimates — plus the re-plan contract validation seam (seeded
corruption in error mode), the service-admission cost weighting, and
the ``aqe-decision`` lint rule.
"""

import glob
import json
import os

import pytest

from spark_rapids_tpu.plan import aqe


def _session(extra=None):
    from spark_rapids_tpu.api.session import TpuSession
    conf = {"spark.rapids.tpu.sql.explain": "NONE"}
    conf.update(extra or {})
    return TpuSession.builder.config(conf).getOrCreate()


def _find(node, klass):
    out = [node] if isinstance(node, klass) else []
    for c in node.children:
        out.extend(_find(c, klass))
    return out


@pytest.fixture(autouse=True)
def _fresh_aqe_tables():
    # cross-execution state (stage history / feedback / costs) is
    # process-global by design; tests must not see each other's runs
    aqe.reset_for_tests()
    yield
    aqe.reset_for_tests()


# ---------------------------------------------------------------------------
# Rule 1: coalesce
# ---------------------------------------------------------------------------

def test_plan_coalesce_groups_adjacent_up_to_target():
    groups = aqe.plan_coalesce([100, 100, 100, 100], 200)
    assert groups == [[0, 1], [2, 3]]


def test_plan_coalesce_tail_merges_into_last_group():
    # the undersized tail must not become its own tiny task
    groups = aqe.plan_coalesce([200, 200, 50], 200)
    assert groups == [[0], [1, 2]]


def test_plan_coalesce_disabled_and_degenerate():
    assert aqe.plan_coalesce([1, 2, 3], 0) == [[0], [1], [2]]
    assert aqe.plan_coalesce([], 100) == []
    # every partition lands in exactly one group (hash disjointness)
    sizes = [10, 500, 10, 10, 10, 700, 10]
    groups = aqe.plan_coalesce(sizes, 300)
    flat = [p for g in groups for p in g]
    assert flat == list(range(len(sizes)))


def test_coalesce_decision_on_aggregate_exchange():
    """A post-join aggregate over tiny partitions merges them and
    records an applied coalesce decision on the exchange."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    s = _session({
        "spark.rapids.tpu.sql.shuffle.partitions": "8",
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
    })
    big = s.createDataFrame({"k": [i % 50 for i in range(2000)],
                             "v": [float(i) for i in range(2000)]})
    dim = s.createDataFrame({"k": list(range(50)),
                             "w": [k * 2.0 for k in range(50)]})
    out = (big.join(dim, on="k", how="inner")
           .groupBy("k").agg(F.sum(col("v") + col("w")).alias("x"))
           .collect())
    assert len(out) == 50
    dec = [d for d in s.last_aqe_decisions() if d["rule"] == "coalesce"]
    assert dec and dec[0]["applied"], s.last_aqe_decisions()
    assert "8 partitions" in dec[0]["before"]
    # and the rule toggle turns it off
    s.conf.set("spark.rapids.tpu.sql.adaptive.coalescePartitions.enabled",
               "false")
    try:
        out2 = (big.join(dim, on="k", how="inner")
                .groupBy("k").agg(F.sum(col("v") + col("w")).alias("x"))
                .collect())
    finally:
        s.conf.set(
            "spark.rapids.tpu.sql.adaptive.coalescePartitions.enabled",
            "true")
    assert sorted(out2) == sorted(out)
    assert not [d for d in s.last_aqe_decisions()
                if d["rule"] == "coalesce"]


# ---------------------------------------------------------------------------
# Rule 2: skew-split
# ---------------------------------------------------------------------------

def test_effective_skew_threshold_factor_raises_cut_line():
    assert aqe.effective_skew_threshold(4096, None, 1000.0) == 4096
    assert aqe.effective_skew_threshold(4096, 5.0, 1000.0) == 5000
    assert aqe.effective_skew_threshold(4096, 5.0, 100.0) == 4096
    assert aqe.effective_skew_threshold(4096, 0.0, 1e9) == 4096


def _skew_conf(extra=None):
    conf = {
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionThreshold":
            "4096",
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
    }
    conf.update(extra or {})
    return conf


def _skewed_frames(s, n=2000):
    ks = [7] * int(n * 0.9) + [i % 40 for i in range(n - int(n * 0.9))]
    vs = [float(i % 13) for i in range(n)]
    big = s.createDataFrame({"k": ks, "v": vs})
    dim = s.createDataFrame({"k": list(range(41)),
                             "w": [k * 10.0 for k in range(41)]})
    from spark_rapids_tpu.api.functions import col
    return (big.join(dim, on="k", how="inner")
            .select(col("k"), (col("v") + col("w")).alias("x")))


def test_skew_split_records_decision_with_bounds():
    from spark_rapids_tpu.plan.physical import TpuShuffledJoinExec
    s = _session(_skew_conf())
    rows = sorted(_skewed_frames(s).collect())
    assert len(rows) == 2000
    dec = [d for d in s.last_aqe_decisions() if d["rule"] == "skew-split"]
    assert dec and dec[0]["applied"], s.last_aqe_decisions()
    assert "hot partition" in dec[0]["after"]
    j = _find(s.last_plan(), TpuShuffledJoinExec)[0]
    m = j.metrics.resolve()
    assert m.get("skewJoinSplits", 0) >= 1
    # split bound: the hot partition splits into at most 64 chunks
    assert j.aqe_skew_factor == 5.0


def test_skew_factor_suppresses_uniformly_large_shuffle():
    """The relative half of the skew test: when every partition is past
    the absolute threshold but none is an outlier vs the median, a huge
    factor must suppress splitting (one uniformly-large shuffle must not
    split everything)."""
    from spark_rapids_tpu.plan.physical import TpuShuffledJoinExec
    s = _session(_skew_conf({
        "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionThreshold":
            "16",
        "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionFactor":
            "1000.0",
    }))
    from spark_rapids_tpu.api.functions import col
    big = s.createDataFrame({"k": list(range(400)) * 5,
                             "v": [float(i) for i in range(2000)]})
    dim = s.createDataFrame({"k": list(range(400)),
                             "w": [k * 1.0 for k in range(400)]})
    rows = big.join(dim, on="k", how="inner") \
        .select(col("k"), (col("v") + col("w")).alias("x")).collect()
    assert len(rows) == 2000
    j = _find(s.last_plan(), TpuShuffledJoinExec)[0]
    assert not j.metrics.resolve().get("skewJoinSplits", 0), \
        "uniform partitions 1000x-factor-gated must not split"


def test_skew_toggle_off_leaves_plan_unstamped():
    from spark_rapids_tpu.plan.physical import TpuShuffledJoinExec
    s = _session(_skew_conf(
        {"spark.rapids.tpu.sql.adaptive.skewJoin.enabled": "false"}))
    rows = sorted(_skewed_frames(s).collect())
    assert len(rows) == 2000
    j = _find(s.last_plan(), TpuShuffledJoinExec)[0]
    assert j.aqe_skew_threshold is None
    assert not [d for d in s.last_aqe_decisions()
                if d["rule"] == "skew-split"]


def test_ici_skew_falls_back_to_dcn_on_repeat_execution():
    """The ICI-plane resolution: the device-resident exchange has no
    host-side sizes, so run 1 declines AND records the stage-stats
    baseline; run 2 reads the prior stats, falls the skewed stage only
    back to DCN, and splits — rows identical both runs."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("mesh needs multiple devices")
    s = _session(_skew_conf({
        "spark.rapids.tpu.sql.mesh.enabled": "true",
        "spark.rapids.tpu.sql.shuffle.plane": "ici",
        # decline the mesh-join route so the join takes ICI-attached
        # hash exchanges (the plane the fallback is about)
        "spark.rapids.tpu.sql.mesh.maxStageBytes": "1024",
    }))
    q = _skewed_frames(s)
    r1 = sorted(q.collect())
    d1 = [d for d in s.last_aqe_decisions() if d["rule"] == "skew-split"]
    assert d1 and not d1[0]["applied"], d1
    assert "first execution records the baseline" in d1[0]["reason"]
    r2 = sorted(q.collect())
    d2 = [d for d in s.last_aqe_decisions() if d["rule"] == "skew-split"]
    assert d2 and d2[0]["applied"], d2
    assert "[ici]" in d2[0]["before"] and "[ici->dcn]" in d2[0]["after"]
    assert r1 == r2 and len(r1) == 2000


# ---------------------------------------------------------------------------
# Rule 3: join-strategy switch (promote + demote, hysteresis)
# ---------------------------------------------------------------------------

def test_join_promote_shuffled_to_broadcast():
    """Estimates keep a 32k-row build side shuffled; its aggregate's
    observed output (50 groups) lands under the threshold -> promote."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.plan.physical import TpuShuffledJoinExec
    s = _session({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "65536",
    })
    big = s.createDataFrame({"k": [i % 50 for i in range(2000)],
                             "v": [float(i) for i in range(2000)]})
    small = (s.createDataFrame({"k": [i % 50 for i in range(32000)],
                                "w": [float(i) for i in range(32000)]})
             .groupBy("k").agg(F.sum(col("w")).alias("w")))
    out = big.join(small, on="k", how="inner").collect()
    assert len(out) == 2000
    j = _find(s.last_plan(), TpuShuffledJoinExec)[0]
    assert j.metrics.resolve().get("runtimeBroadcastJoins", 0) == 1
    dec = [d for d in s.last_aqe_decisions()
           if d["rule"] == "join-promote"]
    assert dec and dec[0]["applied"] and dec[0]["after"] == "broadcast"


def test_join_demote_broadcast_to_shuffled_validated_in_error_mode():
    """Arrow-side estimates say broadcast; device strings pad to the max
    length, so the observed build blows threshold x demoteFactor ->
    demote to a shuffled join whose re-planned stage passes contract
    validation in ERROR mode. Results match the broadcast plan."""
    from spark_rapids_tpu.api.functions import col
    s = _session({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "65536",
        "spark.rapids.tpu.sql.analysis.validatePlan": "error",
    })
    strs = ["x" * (2000 if i == 0 else 2) for i in range(200)]
    fact = s.createDataFrame({"k": [i % 200 for i in range(4000)],
                              "v": [float(i) for i in range(4000)]})
    dim = s.createDataFrame({"k": list(range(200)), "t": strs})
    q = fact.join(dim, on="k", how="inner").select(col("k"), col("v"))
    rows = sorted(q.collect())
    dec = [d for d in s.last_aqe_decisions() if d["rule"] == "join-demote"]
    assert dec and dec[0]["applied"], s.last_aqe_decisions()
    assert dec[0]["before"] == "broadcast" and \
        dec[0]["after"].startswith("shuffled[")
    # no counter-promotion: the demoted replan carries no broadcast
    # threshold, so it cannot flap straight back
    assert not [d for d in s.last_aqe_decisions()
                if d["rule"] == "join-promote"]
    # oracle: same join with the switch rule off (broadcast stands)
    s2 = _session({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "65536",
        "spark.rapids.tpu.sql.adaptive.joinSwitch.enabled": "false",
    })
    fact2 = s2.createDataFrame({"k": [i % 200 for i in range(4000)],
                                "v": [float(i) for i in range(4000)]})
    dim2 = s2.createDataFrame({"k": list(range(200)), "t": strs})
    rows2 = sorted(fact2.join(dim2, on="k", how="inner")
                   .select(col("k"), col("v")).collect())
    assert rows == rows2 and len(rows) == 4000
    assert not s2.last_aqe_decisions()


def test_join_switch_hysteresis_dead_band_no_flap():
    """An observed build inside (threshold, threshold x factor] must
    change nothing on EITHER side of the switch: the shuffled plan stays
    shuffled (declined join-promote), the broadcast plan stays broadcast
    (declined join-demote)."""
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.plan.physical import TpuShuffledJoinExec

    def frames(s):
        strs = ["x" * (2000 if i == 0 else 2) for i in range(200)]
        fact = s.createDataFrame({"k": [i % 200 for i in range(4000)],
                                  "v": [float(i) for i in range(4000)]})
        dim = s.createDataFrame({"k": list(range(200)), "t": strs})
        return fact.join(dim, on="k", how="inner").select(
            col("k"), col("v"))

    # learn the observed build size once (demote rule off so the
    # broadcast plan materializes untouched)
    s0 = _session({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "65536",
        "spark.rapids.tpu.sql.adaptive.joinSwitch.enabled": "false",
    })
    frames(s0).collect()
    from spark_rapids_tpu.shuffle.exchange import TpuBroadcastExchangeExec
    bx = _find(s0.last_plan(), TpuBroadcastExchangeExec)[0]
    observed = int(bx.metrics.resolve().get("dataSize", 0))
    assert observed > 0

    # broadcast side of the band: threshold < observed <= threshold x f
    thr = observed - 1
    factor = 4.0
    assert observed <= thr * factor
    s1 = _session({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": str(thr),
        "spark.rapids.tpu.sql.adaptive.joinSwitch.demoteFactor":
            str(factor),
    })
    rows1 = sorted(frames(s1).collect())
    assert len(rows1) == 4000
    dec = [d for d in s1.last_aqe_decisions()
           if d["rule"] == "join-demote"]
    assert dec and not dec[0]["applied"], s1.last_aqe_decisions()
    assert "hysteresis band" in dec[0]["reason"]
    assert not _find(s1.last_plan(), TpuShuffledJoinExec), \
        "borderline build must stay broadcast"

    # shuffled side of the band: force the shuffled plan (threshold -1 at
    # plan time would disable the switch, so stamp the runtime threshold
    # directly — the existing runtime-broadcast test idiom)
    s2 = _session({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
    })
    plan = frames(s2)._execute()
    j = _find(plan, TpuShuffledJoinExec)[0]
    j.aqe_broadcast_threshold = thr
    j.aqe_demote_factor = factor
    batch = plan.execute_collect()
    assert batch.num_rows == 4000
    dec = [d for d in (j._aqe_decisions or [])
           if d.rule == "join-promote"]
    assert dec and not dec[0].applied
    assert "hysteresis band" in dec[0].reason
    assert not j.metrics.resolve().get("runtimeBroadcastJoins", 0)


def test_replan_seeded_corruption_caught_in_error_mode():
    """The contract seam: corrupt the demoted re-plan (mismatched
    exchange partition counts break the co-partitioning invariant) and
    error-mode validation must reject it before it executes."""
    from spark_rapids_tpu.analysis.contracts import PlanContractError
    from spark_rapids_tpu.api.functions import col

    def corrupt(rep):
        rep.children[1].num_partitions = rep.children[0].num_partitions + 1

    s = _session({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "65536",
        "spark.rapids.tpu.sql.analysis.validatePlan": "error",
    })
    strs = ["x" * (2000 if i == 0 else 2) for i in range(200)]
    fact = s.createDataFrame({"k": [i % 200 for i in range(4000)],
                              "v": [float(i) for i in range(4000)]})
    dim = s.createDataFrame({"k": list(range(200)), "t": strs})
    q = fact.join(dim, on="k", how="inner").select(col("k"), col("v"))
    aqe._REPLAN_CORRUPTION_HOOK = corrupt
    try:
        with pytest.raises(PlanContractError) as ei:
            q.collect()
        assert "AQE re-planned stage" in str(ei.value)
    finally:
        aqe._REPLAN_CORRUPTION_HOOK = None


# ---------------------------------------------------------------------------
# Rule 4: drift feedback
# ---------------------------------------------------------------------------

def _drifty_query(s):
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    df = s.createDataFrame({"k": [i % 7 for i in range(1000)],
                            "v": [float(i) for i in range(1000)]})
    return df.filter(col("k") < 3).groupBy("k").agg(
        F.sum(col("v")).alias("sv"))


def test_drift_feedback_improves_repeat_plan_estimates():
    s = _session()
    q = _drifty_query(s)
    r1 = sorted(q.collect())
    drift1 = {d["operator"]: d for d in s.last_drift_report()}
    assert not [d for d in s.last_aqe_decisions()
                if d["rule"] == "drift-feedback"]
    r2 = sorted(q.collect())
    assert r1 == r2
    dec = [d for d in s.last_aqe_decisions()
           if d["rule"] == "drift-feedback"]
    assert dec and dec[0]["applied"], s.last_aqe_decisions()
    drift2 = {d["operator"]: d for d in s.last_drift_report()}
    # the aggregate's estimate snapped to the observed cardinality:
    # ratio moves to 1.0 on the repeat run
    op = "TpuHashAggregateExec"
    assert abs(drift2[op]["ratio"] - 1.0) < 1e-6, (drift1[op], drift2[op])
    assert abs(drift1[op]["ratio"] - 1.0) > 0.5


def test_drift_feedback_toggle_off():
    s = _session({"spark.rapids.tpu.sql.adaptive.feedback.enabled":
                  "false"})
    q = _drifty_query(s)
    q.collect()
    q.collect()
    assert not [d for d in s.last_aqe_decisions()
                if d["rule"] == "drift-feedback"]


# ---------------------------------------------------------------------------
# Decision surfaces: EXPLAIN ANALYZE, query log, query_report
# ---------------------------------------------------------------------------

def test_decisions_surface_in_explain_log_and_report(tmp_path):
    s = _session(_skew_conf({
        "spark.rapids.tpu.sql.telemetry.queryLog.dir": str(tmp_path),
    }))
    rows = _skewed_frames(s).collect()
    assert len(rows) == 2000
    text = s.explain_analyze()
    assert "* aqe skew-split:" in text, text
    paths = glob.glob(os.path.join(str(tmp_path), "query_log-*.jsonl"))
    assert paths
    rec = json.loads(open(paths[0]).read().splitlines()[-1])
    assert rec["aqe"]["rules"]["skew-split"]["applied"] >= 1
    assert any(d["rule"] == "skew-split" for d in rec["aqe"]["decisions"])
    from tools.query_report import render
    out = render(paths)
    assert "aqe decisions:" in out and "skew-split" in out
    # telemetry counter carries the rule label
    from spark_rapids_tpu.service.telemetry import MetricsRegistry
    snap = MetricsRegistry.get().snapshot()["metrics"]
    rules = {tuple(sorted(s["labels"].items()))
             for s in snap["tpu_aqe_decisions_total"]["samples"]}
    assert (("rule", "skew-split"),) in rules


def test_master_switch_off_disables_every_rule():
    s = _session(_skew_conf(
        {"spark.rapids.tpu.sql.adaptive.enabled": "false"}))
    rows = _skewed_frames(s).collect()
    assert len(rows) == 2000
    assert s.last_aqe_decisions() == []


def test_last_aqe_decisions_requires_an_executed_plan():
    s = _session()
    s._last_exec_plan = None
    with pytest.raises(RuntimeError):
        s.last_aqe_decisions()


# ---------------------------------------------------------------------------
# Service admission cost weighting
# ---------------------------------------------------------------------------

def test_admission_cost_units_unit():
    aqe.reset_for_tests()
    assert aqe.admission_cost_units(None, 1024) == 1
    assert aqe.admission_cost_units("'unknown'", 1024) == 1
    assert aqe.admission_cost_units("'fp'", 0) == 1
    with aqe._history_mu:
        aqe._COSTS["'fp'"] = 10_000
    assert aqe.admission_cost_units("'fp'", 1024) == 1 + 10_000 // 1024
    assert aqe.admission_cost_units("'fp'", 100_000) == 1


def test_observed_expensive_fingerprint_charges_more_on_next_admit():
    """ROADMAP item 1's closing clause: an observed-expensive plan
    fingerprint charges extra queue units against its tenant on the
    NEXT admit of the same label, with the debit counted."""
    from spark_rapids_tpu.service.server import QueryService, TenantSpec
    from spark_rapids_tpu.service.telemetry import MetricsRegistry

    def debits():
        snap = MetricsRegistry.get().snapshot()["metrics"]
        return sum(
            s["value"] for s in snap.get("tpu_admission_cost_debits_total",
                                         {}).get("samples", ()))

    session = _session({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
        "spark.rapids.tpu.sql.service.admission.expensiveBytes": "1024",
    })
    session.createDataFrame(
        {"k": [i % 40 for i in range(2000)],
         "v": [float(i) for i in range(2000)]}).createOrReplaceTempView(
        "aqe_fact")
    session.createDataFrame(
        {"k": list(range(40)),
         "w": [float(k) for k in range(40)]}).createOrReplaceTempView(
        "aqe_dim")
    sql = ("SELECT f.k AS k, sum(f.v + d.w) AS s FROM aqe_fact f "
           "JOIN aqe_dim d ON f.k = d.k GROUP BY f.k")
    svc = QueryService(session, tenants=[
        TenantSpec("t", slots=1, max_queue_depth=256)], max_workers=1)
    try:
        t1 = svc.submit("t", sql, label="hot-join")
        t1.result(timeout=120)
        assert t1.cost == 1, "first admit: fingerprint not yet observed"
        before = debits()
        t2 = svc.submit("t", sql, label="hot-join")
        t2.result(timeout=120)
        assert t2.cost > 1, \
            "observed-expensive fingerprint must charge more than 1 unit"
        assert debits() - before == t2.cost - 1
        # the cost-weighted queue drains back to zero
        assert svc.stats()["tenants"]["t"]["queued"] == 0
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# aqe-decision lint rule
# ---------------------------------------------------------------------------

def test_lint_aqe_decision_rule():
    from spark_rapids_tpu.analysis import lint
    decl = 'AQE_RULES = ("coalesce", "skew-split")\n'
    ok_use = 'record_decision(n, "coalesce", reason="x")\n'
    bad_use = 'aqe.record_decision(n, "made-up-rule")\n'
    sources = {
        "plan/aqe.py": ("plan/aqe.py", decl + ok_use),
        "plan/physical.py": ("plan/physical.py", bad_use),
    }
    out = lint.check_aqe_rules(sources)
    assert len(out) == 1 and out[0].rule == "aqe-decision"
    assert "made-up-rule" in out[0].message
    # declared-everywhere -> clean; missing declaration -> violation
    sources["plan/physical.py"] = (
        "plan/physical.py", 'record_decision(n, "skew-split")\n')
    assert lint.check_aqe_rules(sources) == []
    sources["plan/aqe.py"] = ("plan/aqe.py", ok_use)
    out = lint.check_aqe_rules(sources)
    assert len(out) == 1 and "AQE_RULES" in out[0].message
    # no adaptive subsystem at all -> no findings
    assert lint.check_aqe_rules({}) == []


def test_shipped_tree_passes_aqe_decision_lint():
    import spark_rapids_tpu
    from spark_rapids_tpu.analysis import lint
    pkg = os.path.dirname(spark_rapids_tpu.__file__)
    sources = {}
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, pkg).replace(os.sep, "/")
                with open(full) as f:
                    sources[rel] = (full, f.read())
    assert lint.check_aqe_rules(sources) == []
    # every rule the package uses is also exercised-declared
    declared = lint.aqe_declared_rules(sources["plan/aqe.py"][1])
    assert declared == set(aqe.AQE_RULES)
