"""ARRAY type + generate/explode + split (VERDICT r3 item 8; ref:
GpuGenerateExec.scala, complexTypeExtractors.scala)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col

from golden import assert_tpu_and_cpu_equal


def _array_table():
    return pa.table({
        "k": [1, 2, 3, 4, 5],
        "a": pa.array([[1, 2, 3], [], None, [7], [8, 9]],
                      type=pa.list_(pa.int64())),
    })


def test_explode_array_golden():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_array_table())
        .select(col("k"), F.explode(col("a")).alias("v")))


def test_posexplode_array_golden():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_array_table())
        .select(col("k"), F.posexplode(col("a"))))


def test_get_array_item_and_size_golden():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_array_table())
        .select(col("k"), F.get_item(col("a"), 1).alias("second"),
                F.size(col("a")).alias("n")))


def test_explode_split_fused_golden():
    """explode(split(s, ',')): the fused device kernel, incl. empty parts,
    empty strings, and NULLs."""
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame({
            "id": [1, 2, 3, 4, 5],
            "s": ["a,bb,ccc", "", None, "x", ",y,"]})
            .select(col("id"), F.explode(F.split(col("s"), ",")).alias("w")))

    assert_tpu_and_cpu_equal(q)
    captured["s"].assert_on_tpu()


def test_posexplode_split_positions():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({
            "s": ["one two", "three", "a b c d"]})
        .select(F.posexplode(F.split(col("s"), " "))))


def test_explode_then_groupby():
    """Generated rows feed a downstream aggregate."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({
            "s": ["a,b,a", "b,c", "a"]})
        .select(F.explode(F.split(col("s"), ",")).alias("w"))
        .groupBy("w").agg(F.count("*").alias("n")))


def test_split_outside_generate_falls_back():
    """Standalone split() (no explode) runs on the CPU engine."""
    def q(s):
        return (s.createDataFrame({"s": ["a,b", "c"]})
                .select(F.size(F.split(col("s"), ",")).alias("n")))
    assert_tpu_and_cpu_equal(q, expect_fallback=["Project"])


def test_array_roundtrip_arrow():
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    t = _array_table()
    b = ColumnarBatch.from_arrow(t)
    back = b.to_arrow()
    assert back.column("a").to_pylist() == t.column("a").to_pylist()


def test_explode_large_random_golden():
    rng = np.random.default_rng(31)
    arrays = [None if rng.random() < 0.1 else
              [int(x) for x in rng.integers(0, 100, rng.integers(0, 6))]
              for _ in range(800)]
    t = pa.table({"k": list(range(800)),
                  "a": pa.array(arrays, type=pa.list_(pa.int64()))})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(t)
        .select(col("k"), F.posexplode(col("a"))))


def test_array_null_elements_roundtrip():
    """VERDICT r4 item 10: NULL array elements round-trip device-side
    (element-validity matrix), through element_at and explode."""
    import pyarrow as pa
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col

    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    arr = pa.array([[1, None, 3], None, [None], [4, 5]],
                   type=pa.list_(pa.int64()))
    df = s.createDataFrame(pa.table({"id": [1, 2, 3, 4], "a": arr}))
    # collect round-trips the NULL elements
    out = df.collect()
    assert out == [(1, [1, None, 3]), (2, None), (3, [None]), (4, [4, 5])]
    # element_at: present-but-NULL element -> NULL
    got = df.select(col("id"), F.element_at(col("a"), 2).alias("e")
                    ).collect()
    assert got == [(1, None), (2, None), (3, None), (4, 5)]
    # explode keeps NULL elements as NULL rows (only NULL/empty arrays
    # produce no rows)
    ex = (df.select(col("id"), F.explode(col("a")).alias("v"))
          .collect())
    assert sorted(ex, key=repr) == sorted(
        [(1, 1), (1, None), (1, 3), (3, None), (4, 4), (4, 5)], key=repr)
