"""Auxiliary subsystems: ML export, compression codecs, tracing spans."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F


def _session():
    return TpuSession.builder.config(
        "spark.rapids.tpu.sql.explain", "NONE").getOrCreate()


# -- ML export (ColumnarRdd / InternalColumnarRddConverter analog) -----------

def test_to_feature_matrix_and_labels():
    from spark_rapids_tpu import models
    s = _session()
    df = s.createDataFrame(pd.DataFrame({
        "a": [1.0, 2.0, None, 4.0],
        "b": [10, 20, 30, 40],
        "y": [0.0, 1.0, 0.0, 1.0]}))
    feats, labels = models.to_feature_matrix(df, label_col="y")
    f = np.asarray(feats)
    assert f.shape == (4, 2) and f.dtype == np.float32
    assert np.isnan(f[2, 0])            # NULL -> NaN (DMatrix missing)
    assert list(np.asarray(labels)) == [0.0, 1.0, 0.0, 1.0]


def test_to_device_arrays_stays_on_device():
    import jax
    from spark_rapids_tpu import models
    s = _session()
    df = s.createDataFrame({"x": [1, 2, 3]})
    arrays = models.to_device_arrays(df)
    data, valid = arrays["x"]
    assert isinstance(data, jax.Array)
    assert list(np.asarray(data)) == [1, 2, 3]


def test_to_torch():
    from spark_rapids_tpu import models
    s = _session()
    df = s.createDataFrame(pd.DataFrame({"a": [1.0, 2.0], "y": [0.0, 1.0]}))
    feats, labels = models.to_torch(df, label_col="y")
    assert feats.shape == (2, 1)
    assert labels.tolist() == [0.0, 1.0]


def test_feature_matrix_rejects_strings():
    from spark_rapids_tpu import models
    s = _session()
    df = s.createDataFrame({"a": [1.0], "s": ["x"]})
    with pytest.raises(TypeError):
        models.to_feature_matrix(df, feature_cols=["s"])


# -- compression codecs ------------------------------------------------------

def test_codec_roundtrip():
    from spark_rapids_tpu.shuffle.compression import get_codec
    data = bytes(range(256)) * 100
    for name in ("none", "zlib"):
        c = get_codec(name)
        enc = c.compress(data)
        assert c.decompress(enc, len(data)) == data
    z = get_codec("zlib")
    assert len(z.compress(b"a" * 10000)) < 200


def test_unknown_codec_rejected():
    from spark_rapids_tpu.shuffle.compression import get_codec
    with pytest.raises(ValueError):
        get_codec("snappy")


def test_transport_with_zlib_codec():
    """Server compresses chunk payloads; client transparently decompresses
    (CRC covers the wire form)."""
    import socket
    import threading
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.shuffle.transport import (ShuffleClient,
                                                    ShuffleServer,
                                                    ShuffleStore,
                                                    SocketConnection)
    store = ShuffleStore()
    batch = ColumnarBatch.from_pydict({
        "a": list(range(5000)), "b": [0.5] * 5000})
    store.register_batch(9, 0, batch)
    srv = ShuffleServer(store, chunk_bytes=4096, codec="zlib")

    def connect():
        a, b = socket.socketpair()
        threading.Thread(target=srv.handle_connection,
                         args=(SocketConnection(b),), daemon=True).start()
        return SocketConnection(a)

    got = ShuffleClient(connect).fetch(9, [0])
    assert sorted(got[0].rows()) == sorted(batch.rows())


def test_spill_disk_compression(tmp_path):
    import os
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.exec.spill import BufferCatalog, \
        SpillableColumnarBatch
    cat = BufferCatalog(device_budget=1 << 30, host_budget=1 << 30,
                        spill_dir=str(tmp_path))
    # highly compressible payload
    b = ColumnarBatch.from_pydict({"x": [7] * 4096})
    s = SpillableColumnarBatch(b, catalog=cat)
    import os as _os
    _os.environ["SPARK_RAPIDS_TPU_CONF__SPARK__RAPIDS__TPU__MEMORY__SPILL__COMPRESSION__CODEC"] = "zlib"
    try:
        buf = cat.buffers[s._id]
        buf.spill_to_host()
        buf.spill_to_disk(str(tmp_path))
        files = list(tmp_path.glob("spill-*.npz"))
        assert files
        assert files[0].stat().st_size < b.device_size_bytes() / 4
        back = s.get_batch()
        assert back.rows() == b.rows()
    finally:
        del _os.environ[
            "SPARK_RAPIDS_TPU_CONF__SPARK__RAPIDS__TPU__MEMORY__SPILL__COMPRESSION__CODEC"]
        s.close()


# -- tracing -----------------------------------------------------------------

def test_trace_span_noop_and_enabled():
    from spark_rapids_tpu.exec import tracing
    tracing.reset_cache()
    with tracing.trace_span("test-span"):
        x = 1 + 1
    assert x == 2
    # forced on: spans must still nest/execute correctly
    import os
    os.environ["SPARK_RAPIDS_TPU_CONF__SPARK__RAPIDS__TPU__SQL__TRACING__ENABLED"] = "true"
    tracing.reset_cache()
    try:
        with tracing.trace_span("outer"):
            with tracing.trace_span("inner"):
                x = 2 + 2
        assert x == 4
    finally:
        del os.environ[
            "SPARK_RAPIDS_TPU_CONF__SPARK__RAPIDS__TPU__SQL__TRACING__ENABLED"]
        tracing.reset_cache()


# -- regexp_replace + api_validation -----------------------------------------

def test_regexp_replace_golden():
    from golden import assert_tpu_and_cpu_equal
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"s": ["ab12cd", "x9", None, "zz"]})
        .select(F.regexp_replace(F.col("s"), r"\d+", "#").alias("r")),
        conf={"spark.rapids.tpu.sql.incompatibleOps.enabled": "true"})


def test_regexp_replace_group_refs():
    from golden import assert_tpu_and_cpu_equal
    rows = assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"s": ["a-b", "c-d"]})
        .select(F.regexp_replace(F.col("s"), r"(\w)-(\w)", "$2_$1")
                .alias("r")),
        conf={"spark.rapids.tpu.sql.incompatibleOps.enabled": "true"})
    assert sorted(r[0] for r in rows) == ["b_a", "d_c"]


def test_api_validation_tool():
    from tools.api_validation import validate
    report = validate()
    assert report["ok"], report["problems"]
    assert report["n_expressions"] > 100
    assert report["n_execs"] >= 15


def test_last_query_metrics_surfaced():
    """Per-query SQLMetrics analog (ref GpuMetricNames, GpuExec.scala:27-56):
    operator counters surface in plan order with memory-runtime totals."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col

    s = TpuSession.builder.getOrCreate()
    df = s.createDataFrame({"k": [1, 2, 1, 3] * 50, "v": [1.0] * 200})
    df.filter(col("v") > 0).groupBy("k").agg(
        F.sum("v").alias("s")).collect()
    rep = s.last_query_metrics()
    ops = {o["operator"].split("[")[0]: o["metrics"] for o in rep["operators"]}
    assert any("HashAggregate" in name for name in ops), ops.keys()
    agg = next(m for name, m in ops.items() if "HashAggregate" in name)
    assert agg.get("numOutputRows") == 3
    assert "computeAggTime" in agg
    scan = next(m for name, m in ops.items() if "Scan" in name)
    assert scan.get("numOutputRows") == 200
    assert set(rep["memory"]) == {"deviceBytesHeld", "hostBytesHeld",
                                  "spilledDeviceBytes", "spilledHostBytes"}
    text = s.explain_metrics()
    assert "numOutputRows" in text and "memory:" in text


def test_hash_optimize_sort_insertion():
    """HashSortOptimizeSuite analog: with hashOptimizeSort.enabled a local
    sort lands above hash-agg outputs; results unchanged; default off."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.plan.physical import TpuSortExec

    data = {"k": [3, 1, 2, 1] * 10, "v": [1.0] * 40}

    s1 = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.hashOptimizeSort.enabled": "true"}).getOrCreate()
    out = dict(s1.createDataFrame(data).groupBy("k").agg(
        F.sum("v").alias("sv")).collect())
    assert out == {1: 20.0, 2: 10.0, 3: 10.0}

    def has_sort_above_agg(node):
        if isinstance(node, TpuSortExec) and not node.is_global:
            return True
        return any(has_sort_above_agg(c) for c in node.children)
    assert has_sort_above_agg(s1.last_plan())
    s1.stop()

    s2 = TpuSession.builder.getOrCreate()
    s2.createDataFrame(data).groupBy("k").agg(F.sum("v").alias("sv")).collect()
    assert not has_sort_above_agg(s2.last_plan())


def test_dataframe_cache_golden():
    """df.cache(): later queries serve from the materialized in-memory
    table (cache_test analog; ref GpuInMemoryTableScanExec)."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.plan import logical as lp

    s = TpuSession.builder.getOrCreate()
    base = s.createDataFrame({"k": [1, 2, 1, 3] * 25, "v": [2.0] * 100})
    filtered = base.filter(col("v") > 0)
    orig_plan = filtered._plan
    filtered.cache()                 # Spark idiom: in-place side effect
    assert isinstance(filtered._plan, lp.CachedScan)
    out1 = dict(filtered.groupBy("k").agg(F.sum("v").alias("s")).collect())
    out2 = dict(filtered.groupBy("k").agg(F.count("*").alias("c")).collect())
    assert out1 == {1: 100.0, 2: 50.0, 3: 50.0}
    assert out2 == {1: 50, 2: 25, 3: 25}
    # cache of a cache is a no-op; persist accepts a storage level;
    # unpersist restores the original plan
    assert filtered.cache() is filtered
    assert filtered.persist("MEMORY_ONLY") is filtered
    # a frame derived from the cached one keeps working after unpersist
    derived = filtered.groupBy("k").agg(F.count("*").alias("c"))
    filtered.unpersist()
    assert filtered._plan is orig_plan
    assert dict(filtered.groupBy("k").agg(
        F.sum("v").alias("s")).collect()) == out1
    assert dict(derived.collect()) == out2
    # dropping every reference reclaims the cached batch (the session's
    # last-plan capture holds one until the next query replaces it)
    import gc
    import weakref
    owner_ref = weakref.ref(derived._plan.children[0].owner)
    del derived
    s._last_exec_plan = None
    s._last_overrides = None
    gc.collect()
    gc.collect()
    assert owner_ref() is None


def test_span_breakdown_names_query_time():
    """The per-query span report (trace_span -> SpanRecorder) names where
    execute time goes: q1-shaped query must show the hot regions with
    nonzero self time, and span self-times must be nesting-deduplicated
    (each <= executeTimeS-ish wall, not elapsed-of-parent double counts)."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col

    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    df = s.createDataFrame({
        "k": [i % 5 for i in range(1000)],
        "v": [float(i % 97) for i in range(1000)]})
    (df.filter(col("v") > 3)
       .groupBy("k")
       .agg(F.sum("v").alias("sv"), F.avg("v").alias("av"))
       .orderBy("k").collect())
    m = s.last_query_metrics()
    spans = m["spans"]
    assert spans, "span report must not be empty"
    # reserved query-level scalars ride next to the per-name records
    assert spans["wallS"] > 0.0 and spans["concurrency"] >= 0.0
    for name, rec in spans.items():
        if name in ("wallS", "concurrency"):
            continue
        assert rec["selfS"] >= 0.0 and rec["count"] >= 1, (name, rec)
    # the aggregate/sort pipeline must be named
    assert any(n in spans for n in ("aggregate", "fused_project",
                                    "fused_filter_project", "sort",
                                    "op_TpuSortExec")), spans
