"""Bench history + regression gate (ISSUE 7, benchmarks/history.py):
round-over-round verdicts against the best prior clean same-backend
round, with degraded/errored rounds recorded but never judged or used
as baselines."""

import json
import os

from benchmarks import history as bh


def _clean_round(kind="bench", backend="axon", **queries):
    return bh.round_entry(kind, queries, backend=backend)


def test_verdicts_clean_to_clean_improvement(tmp_path):
    path = str(tmp_path / "h.jsonl")
    bh.append(_clean_round(fused=100.0), path)
    gate = bh.stamp("bench", {"fused": 120.0}, backend="axon", path=path)
    v = gate["verdicts"]["fused"]
    assert v["verdict"] == "improvement"
    assert v["baseline"] == 100.0 and v["changePct"] == 20.0
    assert gate["overall"] == "improvement"
    # ... and the new round became history: a same-value follow-up is ok
    gate2 = bh.stamp("bench", {"fused": 120.0}, backend="axon", path=path)
    assert gate2["verdicts"]["fused"]["verdict"] == "ok"
    assert gate2["verdicts"]["fused"]["baseline"] == 120.0


def test_seeded_regression_warns_and_fails(tmp_path):
    path = str(tmp_path / "h.jsonl")
    bh.append(_clean_round(fused=200.0), path)
    # 12% down: warn
    warn = bh.stamp("bench", {"fused": 176.0}, backend="axon", path=path)
    assert warn["verdicts"]["fused"]["verdict"] == "warn"
    # 30% down vs the BEST prior clean round (still 200): fail
    fail = bh.stamp("bench", {"fused": 140.0}, backend="axon", path=path)
    v = fail["verdicts"]["fused"]
    assert v["verdict"] == "fail" and v["baseline"] == 200.0
    assert fail["overall"] == "fail"


def test_degraded_round_excluded_from_baseline_and_never_judged(tmp_path):
    path = str(tmp_path / "h.jsonl")
    bh.append(_clean_round(fused=200.0), path)
    # a dark round: measured, labeled, recorded ...
    dark = bh.stamp("bench", {"fused": 3.0}, backend="axon",
                    degraded=True, error="tunnel unreachable", path=path)
    assert dark["verdicts"]["fused"]["verdict"] == "excluded"
    # ... but the NEXT clean round is judged against 200, not 3
    nxt = bh.stamp("bench", {"fused": 198.0}, backend="axon", path=path)
    v = nxt["verdicts"]["fused"]
    assert v["baseline"] == 200.0 and v["verdict"] == "ok"


def test_backend_series_never_cross(tmp_path):
    """A cpu round must not be judged against an accelerator baseline
    (and vice versa) — cross-backend comparison is noise."""
    path = str(tmp_path / "h.jsonl")
    bh.append(_clean_round(fused=200.0, backend="axon"), path)
    cpu = bh.stamp("bench", {"fused": 2.0}, backend="cpu", path=path)
    assert cpu["verdicts"]["fused"]["verdict"] == "no-baseline"


def test_lower_is_better_direction(tmp_path):
    """Runner series store hot SECONDS: lower is better, so a higher
    value regresses."""
    path = str(tmp_path / "h.jsonl")
    bh.append(bh.round_entry("runner-tpch-sf0.01", {"q1": 1.0},
                             backend="cpu", higher_is_better=False), path)
    worse = bh.stamp("runner-tpch-sf0.01", {"q1": 1.4}, backend="cpu",
                     higher_is_better=False, path=path)
    assert worse["verdicts"]["q1"]["verdict"] == "fail"
    better = bh.stamp("runner-tpch-sf0.01", {"q1": 0.8}, backend="cpu",
                      higher_is_better=False, path=path)
    v = better["verdicts"]["q1"]
    assert v["verdict"] == "improvement" and v["baseline"] == 1.0


def test_zeroed_and_missing_values(tmp_path):
    """A zero value (the old dark-round artifact shape) is never a
    baseline and reads no-measurement when judged."""
    path = str(tmp_path / "h.jsonl")
    bh.append(_clean_round(fused=0.0), path)          # zeroed clean round
    gate = bh.stamp("bench", {"fused": 50.0, "other": 0.0},
                    backend="axon", path=path)
    assert gate["verdicts"]["fused"]["verdict"] == "no-baseline"
    assert gate["verdicts"]["other"]["verdict"] == "no-measurement"


def test_history_tolerates_corrupt_lines(tmp_path):
    path = str(tmp_path / "h.jsonl")
    bh.append(_clean_round(fused=100.0), path)
    with open(path, "a") as f:
        f.write("{torn json line\n")
        f.write("42\n")
    bh.append(_clean_round(fused=110.0), path)
    h = bh.load(path)
    assert [e["queries"]["fused"] for e in h] == [100.0, 110.0]
    assert bh.baseline(h, "bench", "axon", "fused") == 110.0


def test_stamp_appends_round_with_verdict_summary(tmp_path):
    path = str(tmp_path / "h.jsonl")
    bh.stamp("bench", {"fused": 100.0}, backend="axon", path=path,
             meta={"rows": 123})
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert len(lines) == 1
    assert lines[0]["queries"] == {"fused": 100.0}
    assert lines[0]["regression"] == {"fused": "no-baseline"}
    assert lines[0]["meta"] == {"rows": 123}


def test_committed_seed_history_gates_the_next_round():
    """The repo ships benchmarks/reports/bench_history.jsonl seeded from
    BENCH_r01..r05: the next clean axon round must be judged against the
    best prior clean round (r02, 221.13 Mrows/s) with the two dark
    rounds (r04/r05) excluded."""
    h = bh.load(bh.DEFAULT_PATH)
    assert len(h) >= 5
    base = bh.baseline(h, "bench", "axon", "fused_pipeline")
    assert base == 221.13
    # a 30%-down next round would FAIL loudly instead of shipping dark
    v = bh.verdict_for(154.0, base)
    assert v["verdict"] == "fail"


def test_default_path_honors_env(tmp_path, monkeypatch):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_BENCH_HISTORY",
                       str(tmp_path / "env.jsonl"))
    assert bh.default_path() == str(tmp_path / "env.jsonl")
    bh.stamp("bench", {"fused": 1.0}, backend="cpu")
    assert os.path.exists(str(tmp_path / "env.jsonl"))
