"""Columnar container tests: Column/ColumnarBatch round-trips, bucketing, nulls.

Reference analog: GpuColumnVector / batch conversion tests plus FuzzerUtils-style
round trips (SURVEY.md §4 ring 1).
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, Scalar, bucket


def test_bucket():
    assert bucket(0) == 128
    assert bucket(1) == 128
    assert bucket(128) == 128
    assert bucket(129) == 256
    assert bucket(1000) == 1024


def test_numeric_roundtrip():
    vals = [1, 2, None, -4, 5]
    col = Column.from_pylist(vals, dt.INT64)
    assert col.capacity == 128
    assert col.to_pylist(5) == vals


def test_float_nan_stays_valid():
    vals = [1.0, float("nan"), None]
    col = Column.from_pylist(vals, dt.FLOAT64)
    out = col.to_pylist(3)
    assert out[0] == 1.0
    assert np.isnan(out[1])
    assert out[2] is None


def test_string_roundtrip():
    vals = ["hello", "", None, "world!", "a"]
    col = Column.from_pylist(vals, dt.STRING)
    assert col.to_pylist(5) == vals
    assert col.data.shape[1] == 8  # MIN_STRING_WIDTH bucket


def test_string_unicode():
    vals = ["héllo", "日本語", None]
    col = Column.from_pylist(vals, dt.STRING)
    assert col.to_pylist(3) == vals


def test_batch_from_pydict_and_arrow():
    b = ColumnarBatch.from_pydict({
        "i": [1, 2, 3], "f": [1.5, None, 2.5], "s": ["x", "y", None]})
    assert b.num_rows == 3
    assert b.schema.names() == ["i", "f", "s"]
    tbl = b.to_arrow()
    b2 = ColumnarBatch.from_arrow(tbl)
    assert b2.to_pydict() == b.to_pydict()


def test_batch_from_arrow_types():
    tbl = pa.table({
        "b": pa.array([True, None, False]),
        "i32": pa.array([1, 2, 3], type=pa.int32()),
        "d": pa.array([0, 1, None], type=pa.date32()),
        "ts": pa.array([0, 1_000_000, None], type=pa.timestamp("us")),
    })
    b = ColumnarBatch.from_arrow(tbl)
    assert b.schema["b"].dtype == dt.BOOL
    assert b.schema["i32"].dtype == dt.INT32
    assert b.schema["d"].dtype == dt.DATE
    assert b.schema["ts"].dtype == dt.TIMESTAMP
    assert b.column("d").to_pylist(3) == [0, 1, None]
    assert b.column("ts").to_pylist(3) == [0, 1_000_000, None]


def test_scalar_column():
    col = Column.from_scalar(Scalar(7, dt.INT32), 5, 128)
    assert col.to_pylist(5) == [7] * 5
    null = Column.from_scalar(Scalar(None, dt.INT64), 3, 128)
    assert null.to_pylist(3) == [None] * 3


def test_padding_is_invalid_and_zeroed():
    col = Column.from_pylist([9, 9], dt.INT64)
    assert not bool(np.asarray(col.validity)[2:].any())
    assert not np.asarray(col.data)[2:].any()


def test_type_promotion():
    assert dt.promote(dt.INT32, dt.INT64) == dt.INT64
    assert dt.promote(dt.INT64, dt.FLOAT32) == dt.FLOAT32
    assert dt.promote(dt.INT8, dt.BOOL) == dt.INT8
    with pytest.raises(ValueError):
        dt.promote(dt.STRING, dt.INT32)
