"""Compile-time & HBM discipline (ISSUE 10, docs/compile.md): the
persistent compile cache round trip, buffer donation, and the
capacity-bucket compile-once invariant."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fresh_session(**conf):
    from spark_rapids_tpu.api.session import TpuSession
    base = {"spark.rapids.tpu.sql.explain": "NONE"}
    base.update(conf)
    return TpuSession.builder.config(base).getOrCreate()


@pytest.fixture
def default_compile_conf():
    """Restore the default compile gates after a test flips them (the
    donation/cacheDir primes are process-global)."""
    yield
    from spark_rapids_tpu.exec import compile_cache
    _fresh_session()
    compile_cache.configure(None)


# ---------------------------------------------------------------------------
# Persistent cache round trip across a process restart
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, sys, time
t0 = time.time()
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
session = TpuSession.builder.config({
    "spark.rapids.tpu.sql.explain": "NONE",
    "spark.rapids.tpu.sql.compile.cacheDir": sys.argv[1]}).getOrCreate()
import numpy as np
rng = np.random.default_rng(3)
df = session.createDataFrame({
    "k": [int(x) for x in rng.integers(0, 50, 4000)],
    "v": [float(x) for x in rng.normal(0, 10, 4000)]})
out = (df.filter(col("v") > 0).groupBy("k")
       .agg(F.sum("v").alias("s"), F.count("*").alias("c"))
       .collect())
assert len(out) == 50, len(out)
from spark_rapids_tpu.analysis import recompile
rep = recompile.report()
print(json.dumps({
    "wall_s": round(time.time() - t0, 3),
    "cold": sum(v["coldCompiles"] for v in rep.values()),
    "disk": sum(v["diskHits"] for v in rep.values()),
    "compile_s": round(sum(v["compileS"] for v in rep.values()), 3),
    "families": sorted(rep)}))
"""


def _run_child(cache_dir):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("SPARK_RAPIDS_TPU_CONF__SPARK__RAPIDS__TPU__SQL"
            "__ANALYSIS__LOCKDEP", None)
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, cache_dir],
        capture_output=True, text=True, timeout=600, cwd=ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_persistent_cache_round_trip_across_processes(tmp_path):
    """Same shapes in a FRESH process against the same compile.cacheDir:
    zero cold builds — every program classifies as a disk hit (the
    signature index persisted by process 1) — and compile seconds are
    metered in both."""
    cache_dir = str(tmp_path / "compile_cache")
    first = _run_child(cache_dir)
    assert first["cold"] > 0          # the seeding run builds for real
    assert first["compile_s"] > 0
    # jax's on-disk cache wrote executables + our index beside them
    assert os.path.exists(
        os.path.join(cache_dir, "fused_signature_index.jsonl"))
    second = _run_child(cache_dir)
    assert second["cold"] == 0, (
        f"warm restart paid {second['cold']} cold compiles "
        f"(families: {second['families']})")
    assert second["disk"] > 0
    # the warm process loads executables from disk: its compile seconds
    # must undercut the cold run's (a full re-trace would match them)
    assert second["compile_s"] < first["compile_s"]


def test_unwritable_cache_dir_warns_never_fails(caplog,
                                               default_compile_conf):
    """A bad cacheDir logs a loud warning and degrades to in-memory
    caching — the query still runs."""
    import logging
    with caplog.at_level(logging.WARNING, logger="spark_rapids_tpu.compile"):
        session = _fresh_session(**{
            "spark.rapids.tpu.sql.compile.cacheDir": "/dev/null/nope"})
    assert any("not usable" in r.message and "DISABLED" in r.message
               for r in caplog.records)
    from spark_rapids_tpu.exec import compile_cache
    assert compile_cache.active_dir() is None
    rows = session.createDataFrame({"a": [1, 2, 3]}).collect()
    assert [r[0] for r in rows] == [1, 2, 3]


# ---------------------------------------------------------------------------
# Buffer donation
# ---------------------------------------------------------------------------

def _filter_stage():
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.ops import expressions as ex
    from spark_rapids_tpu.ops import predicates as pr
    from spark_rapids_tpu.plan import physical as P
    schema = dt.Schema([dt.Field("v", dt.FLOAT64)])
    pred = pr.GreaterThan(ex.BoundReference(0, dt.FLOAT64, True),
                          ex.Literal(0.0, dt.FLOAT64))
    return schema, P.FusedStage([pred], schema, schema, mode="filter")


def _batch(schema, n, seed=0):
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    rng = np.random.default_rng(seed)
    return ColumnarBatch.from_pydict({"v": rng.normal(0, 1, n)}, schema)


def test_donation_deletes_consumed_buffer(default_compile_conf):
    """A fused filter CONSUMES its input: with compile.donate on
    (default) the batch's device buffers are deleted the moment the
    program ingests them — the eager-HBM-release invariant."""
    _fresh_session()
    schema, stage = _filter_stage()
    b = _batch(schema, 1000)
    arrays = b.flat_arrays()
    res = stage(b)
    assert res is not None
    assert all(a.is_deleted() for a in arrays), \
        "donated input buffers survived the fused call"
    # the output is intact and correct
    cols, count = res
    assert int(count) == int(np.sum(
        np.asarray(_batch(schema, 1000).columns[0].data)[:1000] > 0))


def test_donation_skips_shared_and_origin_batches(default_compile_conf):
    """Catalog-acquired (shared) and scan-cache-served (origin) batches
    must NEVER be donated — their arrays are re-read later."""
    _fresh_session()
    schema, stage = _filter_stage()
    b = _batch(schema, 1000)
    b.shared = True
    arrays = b.flat_arrays()
    assert stage(b) is not None
    assert not any(a.is_deleted() for a in arrays)
    b2 = _batch(schema, 1000, seed=1)
    b2.origin = object()      # any live owner marker
    arrays2 = b2.flat_arrays()
    assert stage(b2) is not None
    assert not any(a.is_deleted() for a in arrays2)


def test_donation_conf_off_keeps_buffers(default_compile_conf):
    _fresh_session(**{"spark.rapids.tpu.sql.compile.donate": "false"})
    schema, stage = _filter_stage()
    b = _batch(schema, 1000)
    arrays = b.flat_arrays()
    assert stage(b) is not None
    assert not any(a.is_deleted() for a in arrays)


def test_spill_acquired_batch_marked_shared():
    """BufferCatalog.acquire_batch marks its batches shared, so the
    donation gate can never free arrays the spill store still owns."""
    from spark_rapids_tpu.exec.spill import SpillableColumnarBatch
    _fresh_session()
    schema, _ = _filter_stage()
    handle = SpillableColumnarBatch(_batch(schema, 256))
    try:
        got = handle.get_batch()
        assert got.shared is True
    finally:
        handle.close()


# ---------------------------------------------------------------------------
# Bucket discipline: ragged sizes share one size class -> one compile
# ---------------------------------------------------------------------------

def test_ragged_batches_share_one_compile(default_compile_conf):
    """Batches of 1000 and 1017 rows both bucket to capacity 1024: the
    second run must compile NOTHING new (the size-class invariant the
    whole discipline exists for)."""
    from spark_rapids_tpu.analysis import recompile
    _fresh_session()
    schema, stage = _filter_stage()
    assert stage(_batch(schema, 1000)) is not None
    snap = recompile.snapshot()
    assert stage(_batch(schema, 1017, seed=2)) is not None
    d = recompile.delta(snap)
    assert sum(v["compiles"] for v in d.values()) == 0, d
    # and both batches really did share the 1024 size class
    assert _batch(schema, 1000).capacity == _batch(schema, 1017).capacity


def test_size_class_audit_traces_unbucketed_dims():
    """The audit names the non-power-of-two dimension that made a
    signature distinct."""
    from spark_rapids_tpu.analysis import recompile
    assert recompile.unbucketed_dims(
        ("fam", ("sig",), 1024, (999, 128))) == [999]
    assert recompile.unbucketed_dims(("fam", 512, 8, 2, True)) == []
