"""Background compile pool (ISSUE 17, docs/compile.md §5): the
deadline-aware routing policy, the eager->compiled mid-stream swap with
lockdep in enforce mode, and pool-build failure fallback."""

import time

import pytest


def _session(extra=None):
    from spark_rapids_tpu.api.session import TpuSession
    conf = {"spark.rapids.tpu.sql.explain": "NONE"}
    conf.update(extra or {})
    return TpuSession.builder.config(conf).getOrCreate()


@pytest.fixture
def pool():
    """A configured pool; restores the delay seam, drains in-flight
    builds and clears failure memory afterwards so later tests see a
    quiet pool (the fused cache keeps whatever landed — harmless)."""
    from spark_rapids_tpu.exec import compile_pool as cp
    _session()
    yield cp
    cp.set_test_build_delay(0.0)
    cp.drain(timeout_s=60.0)
    cp.reset_for_tests()
    _session()


def test_routable_policy(pool):
    """Routing is latency-gated: a plain collect with no deadline keeps
    the synchronous build path byte-identical (the recompile-gate
    invariant); streaming or a tight deadline routes to the pool; a
    deadline with slack to absorb a cold build stays synchronous."""
    from spark_rapids_tpu.exec import query_context as qc
    key = ("stage", ("routable-policy-test-17",), 1024)
    assert not pool.routable(key)
    with qc.streaming_scope():
        assert pool.routable(key)
    with qc.deadline_scope(time.perf_counter() + 0.5):
        assert pool.routable(key)          # < deadlineSlackS remaining
    with qc.deadline_scope(time.perf_counter() + 3600.0):
        assert not pool.routable(key)      # cold build fits the budget
    # pool off: never routable, whatever the context
    _session({"spark.rapids.tpu.sql.compile.async.enabled": "false"})
    try:
        with qc.streaming_scope():
            assert not pool.routable(key)
    finally:
        _session()


def test_async_swap_no_dropped_or_duplicated_rows(pool):
    """The race the pool must win: a streaming query whose fused-stage
    build is held in flight serves its first batches eagerly, swaps to
    the compiled program once the build lands, and the union of
    eager-and-compiled batches is EXACTLY the query result — no row
    dropped at the seam, none produced twice. Lockdep runs in enforce
    mode so an ordering violation in the pool handshake fails loudly."""
    session = _session({
        "spark.rapids.tpu.sql.analysis.lockdep": "enforce"})
    session.range(0, 200_000, 1, numPartitions=8) \
           .createOrReplaceTempView("pool_race_r17")
    # literals unique to this test: the process-global fused cache must
    # not already hold the chain (else nothing routes to the pool)
    sql = ("SELECT id * 7.515625 + 3.25 AS w, id - 17 AS u "
           "FROM pool_race_r17 WHERE id > 1234 AND id < 190123")
    pool.set_test_build_delay(0.4)
    try:
        got = []
        for b in session.sql(sql).collect_iter():
            got.extend(b.rows())
    finally:
        pool.set_test_build_delay(0.0)
    assert pool.drain(timeout_s=60.0)
    st = pool.stats()
    assert st["asyncBuilt"] >= 1, st       # the build really went async
    assert st["failed"] == 0, st
    oracle = session.sql(sql).collect()    # fused-cache hit by now
    assert len(got) == len(oracle)
    assert sorted(got) == sorted(oracle)


def test_pool_build_failure_surfaces_and_is_remembered(pool):
    """A pool build that raises parks the key as 'failed' (so the stage
    raises the real error instead of resubmitting the doomed build every
    batch) and hands the original exception back through failure()."""
    key = ("stage", ("pool-failure-test-17",), 7)

    def boom():
        raise RuntimeError("deliberate pool-build failure")

    st = pool.consult(key, boom, (), "stage")
    assert st == "pending"
    assert pool.drain(timeout_s=60.0)
    assert pool.status(key) == "failed"
    exc = pool.failure(key)
    assert isinstance(exc, RuntimeError)
    assert "deliberate" in str(exc)
    assert pool.stats()["failed"] >= 1
