"""Concurrency discipline tests: runtime lockdep semantics (order-graph,
cycle reports with both stacks, held-across-transfer integration) and a
``not slow``-safe stress test hammering the engine's shared singletons
from a thread pool under ``lockdep=enforce``.

Reference analog: the reference plugin's GpuSemaphore/RapidsBufferCatalog
tests exercise admission + spill under concurrent tasks (SURVEY.md §4);
lockdep is this port's machine-check that the locking those tests rely on
stays deadlock-free.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from spark_rapids_tpu.analysis import lockdep
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.spill import BufferCatalog, StorageTier


@pytest.fixture
def lockdep_mode():
    """Arm a fresh lockdep state; restore the suite's mode after."""
    prev = lockdep.lockdep_mode()
    lockdep.reset_state()

    def arm(mode):
        lockdep.refresh_mode(mode)
        return lockdep

    yield arm
    lockdep.refresh_mode(prev)
    lockdep.reset_state()


def _batch(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return ColumnarBatch.from_pydict({
        "a": rng.integers(0, 1000, n),
        "b": rng.normal(size=n),
    })


# ---------------------------------------------------------------------------
# Lockdep unit semantics
# ---------------------------------------------------------------------------

def test_record_mode_builds_order_graph(lockdep_mode):
    ld = lockdep_mode("record")
    a, b = ld.named_lock("t.graph.A"), ld.named_lock("t.graph.B")
    with a:
        with b:
            pass
    rep = ld.report()
    assert {"edge": "t.graph.A -> t.graph.B", "count": 1} in rep["edges"]
    assert rep["cycles"] == []
    st = rep["locks"]["t.graph.A"]
    assert st["acquires"] == 1 and st["holdS"] >= 0.0


def test_record_mode_detects_inversion_with_both_stacks(lockdep_mode):
    ld = lockdep_mode("record")
    a, b = ld.named_lock("t.inv.A"), ld.named_lock("t.inv.B")
    with a:
        with b:
            pass
    with b:
        with a:                 # reverse order: the inversion
            pass
    cycles = ld.report()["cycles"]
    assert len(cycles) == 1
    c = cycles[0]
    assert c["edge"] == "t.inv.B -> t.inv.A"
    # actionable: BOTH acquisition stacks present and non-empty
    assert "test_concurrency" in c["edgeStack"]
    assert any("test_concurrency" in s for s in c["reverseStacks"].values())


def test_enforce_raises_and_releases_refused_lock(lockdep_mode):
    ld = lockdep_mode("enforce")
    a, b = ld.named_lock("t.enf.A"), ld.named_lock("t.enf.B")
    with a:
        with b:
            pass
    with pytest.raises(lockdep.LockOrderInversionError) as ei:
        with b:
            with a:
                pass
    assert "t.enf.A" in str(ei.value) and "t.enf.B" in str(ei.value)
    # the refused lock must not leak as held
    assert a.acquire(blocking=False)
    a.release()


def test_transitive_inversion_detected(lockdep_mode):
    ld = lockdep_mode("record")
    a = ld.named_lock("t.tri.A")
    b = ld.named_lock("t.tri.B")
    c = ld.named_lock("t.tri.C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:                 # A->B->C->A: a 3-lock cycle
            pass
    assert ld.report()["cycles"], "transitive cycle missed"


def test_rlock_reentry_no_self_edge(lockdep_mode):
    ld = lockdep_mode("record")
    r = ld.named_rlock("t.re.R")
    with r:
        with r:
            pass
    rep = ld.report()
    assert rep["cycles"] == []
    assert rep["locks"]["t.re.R"]["acquires"] == 1


def test_same_name_distinct_locks_are_not_reentrant(lockdep_mode):
    """Re-entrancy is judged by lock OBJECT, not canonical name: nesting
    two INSTANCES of a shared-name lock class (two SpillableBuffer._lock)
    is the ABBA hazard class, so it must record a self-edge (reported as
    a cycle, kernel-lockdep style) and count both acquisitions — not be
    swallowed as a re-entry."""
    ld = lockdep_mode("record")
    a = ld.named_rlock("t.cls.SHARED")
    b = ld.named_rlock("t.cls.SHARED")
    with a:
        with b:
            pass
    rep = ld.report()
    assert rep["locks"]["t.cls.SHARED"]["acquires"] == 2
    assert any(e["edge"] == "t.cls.SHARED -> t.cls.SHARED"
               for e in rep["edges"])
    assert rep["cycles"], "same-class nesting must be reported"
    # release unwinds by identity: both raw locks actually released
    assert not a.locked() and not b.locked()


def test_transfer_under_lock_recorded_and_enforced(lockdep_mode):
    ld = lockdep_mode("record")
    e = ld.named_lock("t.xfer.E")
    with e:
        ld.note_host_transfer("test crossing")
    finds = ld.report()["heldAcrossTransfer"]
    assert finds and finds[0]["locks"] == ["t.xfer.E"]

    ld = lockdep_mode("enforce")
    with pytest.raises(lockdep.LockHeldAcrossTransferError):
        with e:
            ld.note_host_transfer("test crossing")
    with e:                     # sanctioned: no raise
        with ld.allowed_while_locked("documented synchronous design"):
            ld.note_host_transfer("test crossing")


def test_off_mode_is_plain_lock(lockdep_mode):
    ld = lockdep_mode("off")
    a, b = ld.named_lock("t.off.A"), ld.named_lock("t.off.B")
    with b:
        with a:
            pass
    assert ld.report()["edges"] == []


# ---------------------------------------------------------------------------
# Engine stress under enforce: catalog + semaphore + conf from a pool
# ---------------------------------------------------------------------------

def test_engine_singletons_stress_under_enforce(lockdep_mode, tmp_path):
    """Hammer BufferCatalog register/spill/acquire/free, TpuSemaphore
    acquire/release, and TpuConf set/get from a ThreadPoolExecutor with
    lockdep in ``enforce`` mode: any lock-order inversion or unsanctioned
    transfer-under-lock RAISES out of a worker, and the catalog's byte
    accounting must return to zero when every buffer is removed."""
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.exec.device import TpuSemaphore

    ld = lockdep_mode("enforce")
    one = _batch(256).device_size_bytes()
    # budgets sized to force device->host AND host->disk spills mid-run
    cat = BufferCatalog(device_budget=3 * one, host_budget=2 * one,
                        spill_dir=str(tmp_path))
    # long-lived ballast fills the device budget so every worker
    # registration deterministically triggers synchronous spill
    from spark_rapids_tpu.exec.spill import OUTPUT_FOR_SHUFFLE_PRIORITY
    ballast = [cat.register_batch(_batch(256, seed=1000 + i),
                                  OUTPUT_FOR_SHUFFLE_PRIORITY)
               for i in range(3)]
    sem = TpuSemaphore(2)
    conf = cfg.TpuConf()
    n_threads, iters = 4, 6

    def worker(tid):
        for i in range(iters):
            sem.acquire_if_necessary()
            try:
                b = _batch(256, seed=tid * 100 + i)
                bid = cat.register_batch(b)
                out = cat.acquire_batch(bid)
                assert out.num_rows == 256
                conf.set(f"spark.rapids.tpu.test.k{tid}", i)
                assert conf.get_key(f"spark.rapids.tpu.test.k{tid}") == i
                cat.remove(bid)
            finally:
                sem.release_if_necessary()
        return tid

    with ThreadPoolExecutor(max_workers=n_threads,
                            thread_name_prefix="stress") as pool:
        done = list(pool.map(worker, range(n_threads)))
    assert done == list(range(n_threads))

    # spills actually happened (the run exercised the tier moves)...
    assert cat.spilled_device_bytes > 0
    # ...no order inversion was recorded anywhere...
    assert ld.report()["cycles"] == []
    # ...ballast still readable after riding the spill tiers...
    for bid in ballast:
        assert cat.acquire_batch(bid).num_rows == 256
        cat.remove(bid)
    # ...and the accounting drained back to zero
    assert not cat.buffers
    assert cat.device_bytes == 0
    assert cat.host_bytes == 0


def test_stress_graph_has_expected_engine_edges(lockdep_mode, tmp_path):
    """In record mode the same workload documents the engine's sanctioned
    order: catalog admission lock OUTSIDE the per-buffer lock."""
    ld = lockdep_mode("record")
    one = _batch(256).device_size_bytes()
    cat = BufferCatalog(device_budget=2 * one, host_budget=one,
                        spill_dir=str(tmp_path))
    ids = [cat.register_batch(_batch(256, seed=i)) for i in range(4)]
    for i in ids:
        cat.acquire_batch(i)
    for i in ids:
        cat.remove(i)
    edges = {e["edge"] for e in ld.report()["edges"]}
    assert "exec.spill.BufferCatalog._mu -> " \
           "exec.spill.SpillableBuffer._lock" in edges
    assert ld.report()["cycles"] == []


def test_shuffle_server_threads_named_and_joined():
    """Satellite: transport threads carry attributable names and stop()
    joins them bounded (no anonymous daemons left behind)."""
    from spark_rapids_tpu.shuffle.transport import (ShuffleServer,
                                                    ShuffleStore)
    srv = ShuffleServer(ShuffleStore()).start()
    assert srv._accept_thread.name == "tpu-shuffle-accept"
    import socket
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
    try:
        deadline = 50
        names = srv.alive_threads()
        while not any(n.startswith("tpu-shuffle-conn-") for n in names) \
                and deadline:
            import time
            time.sleep(0.05)
            deadline -= 1
            names = srv.alive_threads()
        assert any(n.startswith("tpu-shuffle-conn-") for n in names), names
    finally:
        s.close()
    srv.stop()
    assert not srv._accept_thread.is_alive()
    assert srv.alive_threads() == []


def test_lockdep_bookkeeping_reentry_shield():
    """A GC weakref finalizer can fire INSIDE a lockdep bookkeeping
    section (the state mutex held) and acquire engine locks — e.g. the
    scan-cache eviction closing a spillable. Such an acquisition must
    BYPASS lockdep (raw lock only) instead of re-entering the
    non-reentrant state mutex and hanging the process (observed hang:
    _evict_table -> BufferCatalog.free inside _note_acquired)."""
    from spark_rapids_tpu.analysis import lockdep
    prev = lockdep.lockdep_mode()
    lockdep.refresh_mode("record")
    try:
        lk = lockdep.named_lock("test.shield.reentry")
        with lockdep._mu_section():        # simulate: inside bookkeeping
            assert lockdep._bookkeeping_busy()
            with lk:                       # finalizer-style acquisition:
                pass                       # must not deadlock, untracked
            # creating a lock mid-bookkeeping must not deadlock either
            lockdep.named_lock("test.shield.created-inside")
        assert not lockdep._bookkeeping_busy()
        # the shielded acquisition left no held residue and no stats...
        assert lockdep.stats().get("test.shield.reentry",
                                   {}).get("acquires", 0) == 0
        with lk:                           # ...and normal tracking resumed
            pass
        assert lockdep.stats()["test.shield.reentry"]["acquires"] == 1
    finally:
        lockdep.refresh_mode(prev)


def test_gc_finalizers_enqueue_instead_of_taking_locks():
    """Weakref finalizers (scan-cache eviction, cache-owner close) must
    only ENQUEUE their lock-taking cleanup: fired inline they can
    interrupt a frame that already holds the catalog/watermark locks and
    self-deadlock the thread. The engine drains the queue at safe
    points (partition-task launch, scan-cache access)."""
    from spark_rapids_tpu.exec import spill
    from spark_rapids_tpu.plan.physical import TpuLocalScanExec as Scan
    spill.drain_deferred_finalizers()           # start clean
    closed = []

    class FakeHandle:
        size_bytes = 64

        def close(self):
            closed.append(True)

    key = ("test-evict", ("a",), 1024)
    with Scan._device_cache_lock:
        Scan._DEVICE_CACHE[key] = {"h": FakeHandle()}
        Scan._device_cache_bytes += 64
    # the GC-callback entry point: must not close inline — the frame it
    # interrupts may hold the very locks close() needs
    Scan._evict_table(key)
    assert not closed
    with Scan._device_cache_lock:
        assert key in Scan._DEVICE_CACHE        # still cached: deferred
    spill.drain_deferred_finalizers()           # the safe-point drain
    assert closed == [True]
    with Scan._device_cache_lock:
        assert key not in Scan._DEVICE_CACHE
