"""Datetime expression tests against python datetime oracles."""

import datetime

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Scalar
from spark_rapids_tpu.ops import datetime as D
from spark_rapids_tpu.ops.expressions import col, lit

EPOCH = datetime.date(1970, 1, 1)


def _days(y, m, d):
    return (datetime.date(y, m, d) - EPOCH).days


def _batch_dates(dates):
    sch = dt.Schema([("d", dt.DATE)])
    vals = [None if x is None else _days(*x) for x in dates]
    return ColumnarBatch.from_pydict({"d": vals}, schema=sch)


def _eval(expr, batch):
    expr = expr.transform(
        lambda e: e.resolve(batch.schema) if hasattr(e, "resolve") else None)
    out = expr.eval(batch)
    if isinstance(out, Scalar):
        return out.value
    return out.to_pylist(batch.num_rows)


def test_ymd_extraction():
    b = _batch_dates([(2020, 2, 29), (1969, 12, 31), (2000, 1, 1), None])
    assert _eval(D.Year(col("d")), b) == [2020, 1969, 2000, None]
    assert _eval(D.Month(col("d")), b) == [2, 12, 1, None]
    assert _eval(D.DayOfMonth(col("d")), b) == [29, 31, 1, None]


def test_ymd_wide_range():
    dates = [(1583, 1, 1), (1899, 3, 15), (1970, 1, 1), (2038, 12, 31), (2400, 2, 29)]
    b = _batch_dates(dates)
    assert _eval(D.Year(col("d")), b) == [y for y, _, _ in dates]
    assert _eval(D.Month(col("d")), b) == [m for _, m, _ in dates]
    assert _eval(D.DayOfMonth(col("d")), b) == [d for _, _, d in dates]


def test_dayofweek_quarter_doy():
    # 2024-07-04 is a Thursday: Spark dayofweek=5 (Sun=1), weekday=3 (Mon=0)
    b = _batch_dates([(2024, 7, 4), (2024, 1, 1)])
    assert _eval(D.DayOfWeek(col("d")), b) == [5, 2]
    assert _eval(D.WeekDay(col("d")), b) == [3, 0]
    assert _eval(D.Quarter(col("d")), b) == [3, 1]
    assert _eval(D.DayOfYear(col("d")), b) == [186, 1]


def test_last_day_add_months():
    b = _batch_dates([(2024, 1, 31), (2023, 2, 3)])
    out = _eval(D.LastDay(col("d")), b)
    assert out == [_days(2024, 1, 31), _days(2023, 2, 28)]
    out2 = _eval(D.AddMonths(col("d"), lit(1)), b)
    assert out2 == [_days(2024, 2, 29), _days(2023, 3, 3)]


def test_date_add_sub_diff():
    b = _batch_dates([(2020, 1, 1), None])
    assert _eval(D.DateAdd(col("d"), lit(31)), b) == [_days(2020, 2, 1), None]
    assert _eval(D.DateSub(col("d"), lit(1)), b) == [_days(2019, 12, 31), None]
    b2 = ColumnarBatch.from_pydict(
        {"a": [_days(2020, 3, 1)], "b": [_days(2020, 2, 28)]},
        schema=dt.Schema([("a", dt.DATE), ("b", dt.DATE)]))
    assert _eval(D.DateDiff(col("a"), col("b")), b2) == [2]


def test_timestamp_parts():
    ts = int(datetime.datetime(2021, 6, 15, 13, 45, 59).timestamp())  # UTC env
    micros = ts * 1_000_000
    sch = dt.Schema([("t", dt.TIMESTAMP)])
    b = ColumnarBatch.from_pydict({"t": [micros, None]}, schema=sch)
    assert _eval(D.Hour(col("t")), b) == [13, None]
    assert _eval(D.Minute(col("t")), b) == [45, None]
    assert _eval(D.Second(col("t")), b) == [59, None]
    assert _eval(D.Year(col("t")), b) == [2021, None]


def test_pre_epoch_timestamp_parts():
    # 1969-12-31 23:59:58.5 UTC — floor semantics
    micros = -1_500_000
    sch = dt.Schema([("t", dt.TIMESTAMP)])
    b = ColumnarBatch.from_pydict({"t": [micros]}, schema=sch)
    assert _eval(D.Hour(col("t")), b) == [23]
    assert _eval(D.Minute(col("t")), b) == [59]
    assert _eval(D.Second(col("t")), b) == [58]


def test_unix_timestamp_roundtrip():
    sch = dt.Schema([("t", dt.TIMESTAMP)])
    b = ColumnarBatch.from_pydict({"t": [1_623_764_759_000_000, None]}, schema=sch)
    assert _eval(D.UnixTimestamp(col("t")), b) == [1_623_764_759, None]
    sch2 = dt.Schema([("s", dt.INT64)])
    b2 = ColumnarBatch.from_pydict({"s": [1_623_764_759]}, schema=sch2)
    assert _eval(D.FromUnixTime(col("s")), b2) == [1_623_764_759_000_000]


def test_to_date():
    sch = dt.Schema([("t", dt.TIMESTAMP)])
    b = ColumnarBatch.from_pydict(
        {"t": [86_400_000_000 + 3600_000_000, -1]}, schema=sch)
    # floor: 1970-01-02 and 1969-12-31
    assert _eval(D.ToDate(col("t")), b) == [1, -1]
