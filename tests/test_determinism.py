"""Lockstep-determinism discipline (ISSUE 18): the divergence audit's
digest fold/compare semantics, query-namespaced shuffle-id minting, the
DesyncError recovery contract, and the two-OS-process acceptance runs —
two CONCURRENT distributed queries returning oracle-correct rows under
``divergence=enforce``, and an injected desync surfacing the typed error
naming the first divergent event.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading

import pandas as pd
import pytest

from spark_rapids_tpu.analysis import divergence
from spark_rapids_tpu.analysis.divergence import DesyncError

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_audit():
    divergence.reset()
    yield
    divergence.reset()


# ---------------------------------------------------------------------------
# Digest fold / snapshot / compare units
# ---------------------------------------------------------------------------

def _fold(qid, labels):
    for lb in labels:
        divergence.note_event(lb, query_id=qid)


def test_fold_and_snapshot_shape():
    divergence.install("record")
    _fold("q1", ["a", "b", "c"])
    snap = divergence.snapshot("q1")
    assert snap["count"] == 3
    assert len(snap["digest"]) == 16
    assert [tuple(e)[0::2] for e in snap["ring"]] == \
        [(1, "a"), (2, "b"), (3, "c")]
    # identical streams digest identically; unknown query is the empty
    # stream (the peer treats it as lag)
    _fold("q2", ["a", "b", "c"])
    assert divergence.snapshot("q2")["digest"] == snap["digest"]
    assert divergence.snapshot("q9") == \
        {"count": 0, "digest": "", "ring": []}
    divergence.reset()
    assert divergence.snapshot("q1") is None      # off: no audit surface


def test_check_names_first_divergent_event():
    divergence.install("enforce")
    _fold("q1", ["mint:1", "mint:2", "mint:3"])
    _fold("q2", ["mint:1", "rogue", "mint:3"])    # stand-in peer stream
    peer = divergence.snapshot("q2")
    with pytest.raises(DesyncError) as ei:
        divergence.check("q1", peer, peer_label="worker 1")
    e = ei.value
    assert e.query_id == "q1"
    assert e.first_divergent_index == 2
    assert e.mine[1] == "mint:2" and e.theirs[1] == "rogue"
    assert "mint:2" in str(e) and "rogue" in str(e)
    assert "worker 1" in str(e)
    st = divergence.stats()
    assert st["checks"] == 1 and st["desyncs"] == 1


def test_lag_is_not_divergence():
    divergence.install("enforce")
    _fold("q1", ["a", "b", "c", "d"])
    _fold("q2", ["a", "b"])                       # same prefix, behind
    divergence.check("q1", divergence.snapshot("q2"))
    divergence.check("q2", divergence.snapshot("q1"))
    # a peer that has not folded the query at all is pure lag too
    divergence.check("q1", {"count": 0, "digest": "", "ring": []})
    st = divergence.stats()
    assert st["checks"] == 3 and st["desyncs"] == 0


def test_record_mode_counts_without_raising():
    divergence.install("record")
    _fold("q1", ["a", "b"])
    _fold("q2", ["a", "x"])
    divergence.check("q1", divergence.snapshot("q2"))   # no raise
    assert divergence.stats()["desyncs"] == 1


def test_pre_window_divergence_reports_index_minus_one():
    divergence.install("enforce")
    _fold("q1", ["a", "b"])
    # same event count, non-empty differing digest, NO common ring
    # window: the divergence predates the diagnostic ring
    peer = {"count": 2, "digest": "feedfacecafebeef", "ring": []}
    with pytest.raises(DesyncError) as ei:
        divergence.check("q1", peer)
    assert ei.value.first_divergent_index == -1
    assert "diagnostic window" in str(ei.value)


def test_install_rejects_unknown_mode_and_off_is_noop():
    with pytest.raises(ValueError):
        divergence.install("audit-harder")
    divergence.reset()
    assert not divergence.armed()
    divergence.note_event("a", query_id="q1")     # no-op while off
    divergence.check("q1", {"count": 1, "digest": "ff", "ring": []})
    assert divergence.stats() == \
        {"mode": "off", "checks": 0, "desyncs": 0, "queries": 0}


def test_ring_is_bounded_and_digest_rolls_past_it():
    divergence.install("record")
    _fold("q1", [f"e{i}" for i in range(divergence.RING_CAPACITY + 10)])
    snap = divergence.snapshot("q1")
    assert snap["count"] == divergence.RING_CAPACITY + 10
    assert len(snap["ring"]) == divergence.RING_CAPACITY
    assert snap["ring"][0][0] == 11               # oldest entries evicted


def test_desync_error_classifies_fail_query():
    from spark_rapids_tpu.exec.recovery import RecoveryAction, classify
    e = DesyncError("streams diverged", query_id="q1", index=3,
                    mine=("aa", "x"), theirs=("bb", "y"))
    assert classify(e) is RecoveryAction.FAIL_QUERY


# ---------------------------------------------------------------------------
# Query-namespaced shuffle ids (the concurrent-distributed gating fix)
# ---------------------------------------------------------------------------

def test_shuffle_ids_namespaced_by_query_sequence():
    from spark_rapids_tpu.exec import query_context as qc
    from spark_rapids_tpu.shuffle.manager import NS_SHIFT, WorkerContext
    wc = WorkerContext(0, 1)
    try:
        ctx_a = qc.QueryContext("q000041-aaaaaaaa")
        ctx_b = qc.QueryContext("q000042-bbbbbbbb")
        got_a, got_b = [], []
        # interleave mints across the two ambient queries: each draws
        # from its OWN counter, so the interleaving cannot skew either
        for _ in range(3):
            with qc.query_scope(ctx_a):
                got_a.append(wc.next_shuffle_id())
            with qc.query_scope(ctx_b):
                got_b.append(wc.next_shuffle_id())
        base_a, base_b = 41 << NS_SHIFT, 42 << NS_SHIFT
        assert got_a == [base_a + 1, base_a + 2, base_a + 3]
        assert got_b == [base_b + 1, base_b + 2, base_b + 3]
        # no ambient query -> namespace 0 (direct shuffle-layer callers)
        assert wc.next_shuffle_id() == 1
    finally:
        wc.shutdown()


def test_shuffle_id_mints_fold_into_divergence_stream():
    from spark_rapids_tpu.exec import query_context as qc
    from spark_rapids_tpu.shuffle.manager import NS_SHIFT, WorkerContext
    divergence.install("record")
    wc = WorkerContext(0, 1)
    try:
        with qc.query_scope(qc.QueryContext("q000007-cafecafe")):
            sid = wc.next_shuffle_id()
        snap = divergence.snapshot("q000007-cafecafe")
        assert sid == (7 << NS_SHIFT) + 1
        assert snap["count"] == 1
        assert snap["ring"][0][2] == f"shuffle-id:{sid}"
    finally:
        wc.shutdown()


# ---------------------------------------------------------------------------
# Two OS processes, two CONCURRENT distributed queries (the acceptance
# runs: lockstep-correct under enforce; injected desync surfaces typed)
# ---------------------------------------------------------------------------

_WORKER = """
import sys, json, threading
sys.path.insert(0, {repo!r})
import os
os.environ.setdefault("SPARK_RAPIDS_TPU_COMPILE_CACHE", "off")
from spark_rapids_tpu.shuffle.manager import init_worker

wid = int(sys.argv[1]); n = int(sys.argv[2])
fault = sys.argv[3]; flight_dir = sys.argv[4]
ctx = init_worker(wid, n)
print(json.dumps({{"port": ctx.port}}), flush=True)
peers = json.loads(sys.stdin.readline())
ctx.set_peers({{int(k): tuple(v) for k, v in peers.items()}})

from spark_rapids_tpu.api.session import TpuSession
conf = {{"spark.rapids.tpu.sql.explain": "NONE",
         "spark.rapids.tpu.sql.shuffle.partitions": "4",
         "spark.rapids.tpu.sql.analysis.divergence": "enforce",
         "spark.rapids.tpu.sql.telemetry.flightRecorderDir": flight_dir}}
if fault != "none" and wid == 1:
    # poison ONE lockstep event on THIS worker only: its digest stream
    # now disagrees with worker 0's, and the next META round trip must
    # surface the typed desync
    conf["spark.rapids.tpu.sql.faults.spec"] = fault
s = TpuSession.builder.config(conf).getOrCreate()

base = wid * 1000
ks = [(base + i) % 7 for i in range(200)]
vs = [float(i % 13) for i in range(200)]
s.createDataFrame({{"k": ks, "v": vs}}).createOrReplaceTempView("t")

df_a = s.sql("SELECT k, sum(v) AS sv FROM t GROUP BY k")
df_b = s.sql("SELECT k, count(*) AS c FROM t GROUP BY k")

# the lockstep concurrency discipline (docs/shuffle.md): mint both query
# identities on the MAIN thread in program order — every worker draws
# the same sequence numbers — then collect concurrently under the
# reserved contexts, so the racy collect order never touches the
# query-id counter
from spark_rapids_tpu.exec import query_context as qc
ctx_a = qc.QueryContext(qc.mint_query_id())
ctx_b = qc.QueryContext(qc.mint_query_id())

results = {{}}
def run(name, qctx, df):
    qc.reserve_query(qctx)
    try:
        results[name] = {{"rows": [list(r) for r in df.collect()]}}
    except BaseException as e:
        out = {{"error": type(e).__name__, "msg": str(e),
               "qid": getattr(e, "query_id", None),
               "index": getattr(e, "first_divergent_index", None)}}
        from spark_rapids_tpu.service.telemetry import dump_on_error
        path = dump_on_error(e)
        if path:
            with open(path) as f:
                doc = json.load(f)
            out["dumpQueryId"] = doc.get("queryId")
            out["dumpDesyncEvents"] = sum(
                1 for ev in doc["events"] if ev["kind"] == "desync")
        results[name] = out

ta = threading.Thread(target=run, args=("a", ctx_a, df_a))
tb = threading.Thread(target=run, args=("b", ctx_b, df_b))
ta.start(); tb.start(); ta.join(); tb.join()

from spark_rapids_tpu.analysis import divergence as _div
print(json.dumps({{"wid": wid, "results": results,
                   "stats": _div.stats()}}), flush=True)
ctx.shutdown()
"""


def _run_concurrent_cluster(fault="none", n_workers=2):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    flight_dir = tempfile.mkdtemp(prefix="tpu-flight-determinism-")
    procs = []
    for wid in range(n_workers):
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER.format(repo=_REPO),
             str(wid), str(n_workers), fault, flight_dir],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=env, text=True))
    try:
        ports = {}
        for wid, p in enumerate(procs):
            line = p.stdout.readline()
            assert line, p.stderr.read()
            ports[wid] = ("127.0.0.1", json.loads(line)["port"])
        peers = json.dumps({str(w): list(a) for w, a in ports.items()})
        for p in procs:
            p.stdin.write(peers + "\n")
            p.stdin.flush()
        out = {}
        for p in procs:
            stdout, err = p.communicate(timeout=300)
            for line in stdout.splitlines():
                try:
                    d = json.loads(line)
                    if "wid" in d:
                        out[d["wid"]] = d
                except json.JSONDecodeError:
                    continue
            assert p.returncode == 0, err
        assert set(out) == set(range(n_workers)), out
        return out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def _oracle():
    sh = pd.concat(pd.DataFrame({
        "k": [(wid * 1000 + i) % 7 for i in range(200)],
        "v": [float(i % 13) for i in range(200)]}) for wid in range(2))
    g = sh.groupby("k")
    exp_a = sorted((int(k), float(v)) for k, v in g.v.sum().items())
    exp_b = sorted((int(k), int(v)) for k, v in g.v.count().items())
    return exp_a, exp_b


def test_two_process_concurrent_distributed_queries_enforced():
    """The gating acceptance: TWO distributed queries run CONCURRENTLY
    (threads) across two OS processes under divergence=enforce, and both
    return oracle-correct rows — namespaced shuffle ids keep the two
    id streams disjoint, so the interleaving cannot desync them."""
    out = _run_concurrent_cluster("none")
    rows_a, rows_b = [], []
    for wid, doc in out.items():
        for name, res in doc["results"].items():
            assert "error" not in res, (wid, name, res)
        rows_a.extend(tuple(r) for r in doc["results"]["a"]["rows"])
        rows_b.extend(tuple(r) for r in doc["results"]["b"]["rows"])
        assert doc["stats"]["mode"] == "enforce"
        assert doc["stats"]["desyncs"] == 0
    exp_a, exp_b = _oracle()
    assert sorted(rows_a) == exp_a
    assert sorted(rows_b) == exp_b
    # the audit actually ran: every worker compared digests on its
    # peer round trips
    assert all(doc["stats"]["checks"] > 0 for doc in out.values())


def test_injected_desync_raises_typed_error_with_first_event():
    """Chaos acceptance: one poisoned lockstep event on worker 1
    (faults point desync.inject) surfaces DesyncError on the next
    metadata round trip — typed, naming the first divergent event, with
    the flight-recorder dump scoped to the desynced query."""
    out = _run_concurrent_cluster("desync.inject:1")
    errors = [res
              for doc in out.values()
              for res in doc["results"].values()
              if "error" in res]
    assert errors, out
    assert all(e["error"] == "DesyncError" for e in errors), errors
    # the diagnosis names the injected event at a concrete index
    named = [e for e in errors if "desync.inject" in e["msg"]]
    assert named, errors
    for e in named:
        assert e["index"] is not None and e["index"] >= 1
        assert e["qid"] and e["qid"].startswith("q")
    # the post-mortem artifact is scoped to the desynced query and
    # carries the desync flight event
    dumped = [e for e in errors if e.get("dumpQueryId")]
    assert dumped, errors
    for e in dumped:
        assert e["dumpQueryId"] == e["qid"]
        assert e["dumpDesyncEvents"] >= 1
    # the detecting worker counted the desync
    assert any(doc["stats"]["desyncs"] >= 1 for doc in out.values())
