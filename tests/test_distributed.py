"""Distributed SPMD correctness on the virtual 8-device CPU mesh.

Covers the round-2 judge/advisor findings: distributed avg must carry
sum+count partials (not a sum labeled avg), and the exchange receive window
must admit ``n_workers * cap`` rows so key skew cannot silently drop groups
(VERDICT r2 weak #2/#3).
"""

import collections

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.parallel.mesh import make_mesh, run_distributed_groupby


def _run(shards, agg_ops, val_idx=None, n=8):
    mesh = make_mesh(n)
    return run_distributed_groupby(
        mesh, shards, key_idx=[0],
        val_idx=val_idx if val_idx is not None else [1] * len(agg_ops),
        agg_ops=agg_ops)


def _collect(results, n_aggs=1):
    out = {}
    for r in results:
        d = r.to_pydict()
        for row in zip(d["k0"], *[d[f"a{i}"] for i in range(n_aggs)]):
            k = row[0]
            assert k not in out, f"key {k} owned by two workers"
            out[k] = row[1:]
    return out


def test_distributed_avg_exact():
    """avg over the mesh must equal the global mean per key — the old code
    returned the global SUM labeled avg."""
    rng = np.random.default_rng(3)
    shards = []
    for w in range(8):
        shards.append(ColumnarBatch.from_pydict({
            "k": [int(x) for x in rng.integers(0, 10, 200)],
            "v": [float(x) for x in rng.normal(5, 2, 200)],
        }))
    got = _collect(_run(shards, ["avg"]), n_aggs=1)

    sums = collections.defaultdict(float)
    cnts = collections.defaultdict(int)
    for b in shards:
        d = b.to_pydict()
        for k, v in zip(d["k"], d["v"]):
            sums[k] += v
            cnts[k] += 1
    assert set(got) == set(sums)
    for k in sums:
        expect = sums[k] / cnts[k]
        assert abs(got[k][0] - expect) < 1e-9, \
            f"avg mismatch for {k}: {got[k][0]} vs {expect}"


def test_distributed_avg_with_sum_count():
    """avg alongside sum and count in one pipeline (mixed partial shapes)."""
    rng = np.random.default_rng(11)
    shards = []
    for w in range(8):
        shards.append(ColumnarBatch.from_pydict({
            "k": [int(x) for x in rng.integers(0, 6, 100)],
            "v": [float(x) for x in rng.normal(0, 10, 100)],
        }))
    got = _collect(_run(shards, ["sum", "avg", "count"]), n_aggs=3)
    sums = collections.defaultdict(float)
    cnts = collections.defaultdict(int)
    for b in shards:
        d = b.to_pydict()
        for k, v in zip(d["k"], d["v"]):
            sums[k] += v
            cnts[k] += 1
    for k in sums:
        s, a, c = got[k]
        assert abs(s - sums[k]) < 1e-9
        assert abs(a - sums[k] / cnts[k]) < 1e-9
        assert c == cnts[k]


def _keys_owned_by(worker: int, n_workers: int, count: int):
    """Deterministically pick `count` int keys whose murmur3 hash routes them
    to `worker` — the same hash+mod the mesh exchange uses."""
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.hashing import murmur3_batch
    picked = []
    lo = 0
    while len(picked) < count:
        cand = list(range(lo, lo + 4096))
        col = Column.from_pylist(cand, dt.INT64)
        h = murmur3_batch([col], col.capacity)
        pids = np.asarray(jnp.mod(jnp.mod(h, n_workers) + n_workers,
                                  n_workers))[:len(cand)]
        picked.extend(int(c) for c, p in zip(cand, pids) if p == worker)
        lo += 4096
    return picked[:count]


def test_distributed_groupby_skewed_keys():
    """Every group hashes to ONE owner: the receive window must hold
    n_workers * cap rows (old code capped at cap and silently dropped)."""
    n_workers = 8
    per_worker = 300          # cap = bucket(300) = 512; 8*300 = 2400 > 512
    keys = _keys_owned_by(0, n_workers, n_workers * per_worker)
    shards = []
    for w in range(n_workers):
        ks = keys[w * per_worker:(w + 1) * per_worker]
        shards.append(ColumnarBatch.from_pydict({
            "k": ks,
            "v": [float(k % 7) for k in ks],
        }))
    got = _collect(_run(shards, ["sum", "count"]), n_aggs=2)
    assert len(got) == n_workers * per_worker, \
        f"groups dropped under skew: {len(got)} of {n_workers * per_worker}"
    for k in keys:
        s, c = got[k]
        assert c == 1
        assert abs(s - float(k % 7)) < 1e-9
