"""Planner-driven distributed execution: the Overrides rule plans
partial -> hash exchange -> per-partition final aggregation whenever the
child has more than one partition (the reference's two-phase replaceMode
planning, aggregate.scala:77-170), and the results match the CPU oracle.
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.plan.physical import TpuHashAggregateExec
from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec

from golden import assert_tpu_and_cpu_equal


def _seeded(n=2000, nkeys=37):
    rng = np.random.default_rng(7)
    return pd.DataFrame({
        "k": rng.integers(0, nkeys, n),
        "v": np.where(rng.random(n) < 0.9, rng.normal(0, 10, n), np.nan),
        "j": rng.integers(-5, 5, n),
    })


def _find(node, klass, pred=lambda n: True):
    out = [node] if isinstance(node, klass) and pred(node) else []
    for c in node.children:
        out.extend(_find(c, klass, pred))
    return out


def test_two_phase_agg_planned_over_repartition():
    """repartition(4) -> groupBy must plan partial + exchange + final."""
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(_seeded()).repartition(4)
                .groupBy("k").agg(F.sum("v").alias("s"),
                                  F.avg("v").alias("a"),
                                  F.count("v").alias("c"),
                                  F.min("j").alias("mn"),
                                  F.max("j").alias("mx")))

    assert_tpu_and_cpu_equal(q, approx=1e-9)
    plan = captured["s"].last_plan()
    partials = _find(plan, TpuHashAggregateExec, lambda n: n.mode == "partial")
    finals = _find(plan, TpuHashAggregateExec,
                   lambda n: n.mode == "final" and n.per_partition_final)
    exchanges = _find(plan, TpuShuffleExchangeExec)
    assert partials and finals and exchanges, plan
    assert finals[0].output_partitions > 1


def test_two_phase_global_agg():
    """No grouping keys: partials meet on a single exchange partition."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded()).repartition(5)
        .agg(F.sum("v").alias("s"), F.count("v").alias("c"),
             F.avg("j").alias("aj")),
        approx=1e-9)


def test_two_phase_agg_skewed_keys():
    """Every row carries the SAME key: all partials land on one reduce
    partition and nothing is truncated (skew regression, VERDICT r2 weak #3)."""
    n = 5000
    df = pd.DataFrame({"k": np.ones(n, dtype=np.int64),
                       "v": np.arange(n, dtype=np.float64)})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(df).repartition(8)
        .groupBy("k").agg(F.sum("v").alias("s"), F.count("v").alias("c")),
        approx=1e-9)


def test_two_phase_avg_exact():
    """Distributed avg carries sum+count partials and divides post-merge."""
    df = pd.DataFrame({"k": [1, 1, 2, 2, 2, 3] * 50,
                       "v": [1.0, 2.0, 10.0, 20.0, 30.0, -7.5] * 50})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(df).repartition(4)
        .groupBy("k").agg(F.avg("v").alias("a")),
        approx=1e-12)


def test_two_phase_count_distinct():
    """DISTINCT planning composes with the two-phase distributed plan."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded()).repartition(4)
        .groupBy("j").agg(F.countDistinct("k").alias("cd"),
                          F.sum("v").alias("sv")),
        approx=1e-9)


def test_two_phase_distinct_rows():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(pd.DataFrame({
            "a": [1, 1, 2, 2, 3] * 20, "b": ["x", "x", "y", "z", "x"] * 20}))
        .repartition(3).distinct())


def test_two_phase_agg_null_keys():
    """NULL grouping keys survive the hash exchange as one global group."""
    df = pd.DataFrame({"k": [1.0, None, 2.0, None, 1.0] * 40,
                       "v": np.arange(200, dtype=np.float64)})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(df).repartition(4)
        .groupBy("k").agg(F.sum("v").alias("s"), F.count("v").alias("c")),
        approx=1e-9)


def test_two_phase_agg_first_last_falls_back_cleanly():
    """first/last are order-sensitive; they still work through the two-phase
    plan because partial first/last merge by first/last per partition — but
    cross-partition order is repartition-dependent, so compare only the
    stable aggregates here while asserting the plan executes."""
    def q(s):
        return (s.createDataFrame(_seeded()).repartition(4)
                .groupBy("k").agg(F.min("v").alias("mn")))
    assert_tpu_and_cpu_equal(q, approx=1e-9)


_FORCE_SHUFFLE = {"spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1"}


def _join_frames():
    rng = np.random.default_rng(11)
    left = pd.DataFrame({
        "a": rng.integers(0, 50, 400),
        "x": rng.normal(0, 1, 400)})
    right = pd.DataFrame({
        "b": rng.integers(25, 75, 300),       # half-overlapping key range
        "y": rng.integers(0, 100, 300)})
    return left, right


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_shuffled_join_copartitioned(how):
    """autoBroadcastJoinThreshold=-1 forces the co-partitioned shuffled join
    for every join type; results must match the CPU oracle."""
    left, right = _join_frames()
    captured = {}

    def q(s):
        captured["s"] = s
        l = s.createDataFrame(left)
        r = s.createDataFrame(right)
        return l.join(r, on=(col("a") == col("b")), how=how)

    assert_tpu_and_cpu_equal(q, approx=1e-9, conf=_FORCE_SHUFFLE)
    from spark_rapids_tpu.plan.physical import TpuShuffledJoinExec
    assert _find(captured["s"].last_plan(), TpuShuffledJoinExec), \
        captured["s"].last_plan()


def test_broadcast_join_planned_for_small_build():
    """Small build side -> broadcast exchange appears in the plan."""
    left, right = _join_frames()
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(left)
                .join(s.createDataFrame(right), on=(col("a") == col("b")),
                      how="inner"))

    assert_tpu_and_cpu_equal(q, approx=1e-9)
    from spark_rapids_tpu.shuffle.exchange import TpuBroadcastExchangeExec
    plan = captured["s"].last_plan()
    assert _find(plan, TpuBroadcastExchangeExec), plan


def test_shuffled_join_null_keys():
    """NULL keys co-locate through the hash exchange; outer joins emit them
    with NULL match columns exactly once."""
    left = pd.DataFrame({"a": [1.0, None, 2.0, None, 3.0],
                         "x": [1, 2, 3, 4, 5]})
    right = pd.DataFrame({"b": [2.0, None, 4.0], "y": [10, 20, 30]})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(left)
        .join(s.createDataFrame(right), on=(col("a") == col("b")),
              how="full"),
        conf=_FORCE_SHUFFLE)


def test_shuffled_join_after_repartitioned_agg():
    """Compose: distributed agg feeding a shuffled join."""
    left, right = _join_frames()
    def q(s):
        l = (s.createDataFrame(left).repartition(4)
             .groupBy("a").agg(F.sum("x").alias("sx")))
        return l.join(s.createDataFrame(right),
                      on=(col("a") == col("b")), how="inner")
    assert_tpu_and_cpu_equal(q, approx=1e-9, conf=_FORCE_SHUFFLE)


# -- distributed sort via range exchange -------------------------------------

def test_distributed_sort_total_order():
    """Global sort over a multi-partition child plans range exchange +
    per-partition sort; collected rows are totally ordered."""
    rng = np.random.default_rng(3)
    df = pd.DataFrame({"k": rng.permutation(3000),
                       "v": rng.normal(0, 1, 3000)})
    captured = {}

    def q(s):
        captured["s"] = s
        return s.createDataFrame(df).repartition(5).orderBy("k")

    assert_tpu_and_cpu_equal(q, approx=1e-12, ignore_order=False)
    from spark_rapids_tpu.shuffle.exchange import TpuRangeExchangeExec
    assert _find(captured["s"].last_plan(), TpuRangeExchangeExec)


def test_distributed_sort_desc_nulls():
    rng = np.random.default_rng(5)
    vals = rng.normal(0, 100, 800)
    vals[rng.random(800) < 0.1] = np.nan
    df = pd.DataFrame({"k": np.where(np.isnan(vals), np.nan, vals),
                       "i": np.arange(800)})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(df).repartition(4)
        .orderBy(F.col("k").desc(), F.col("i")),
        approx=1e-12, ignore_order=False)


def test_distributed_sort_skewed_values():
    """90% duplicate keys: range bounds collapse but no rows are lost; ties
    broken by a secondary unique key keep the comparison deterministic."""
    rng = np.random.default_rng(6)
    k = np.where(rng.random(2000) < 0.9, 7, rng.integers(0, 100, 2000))
    df = pd.DataFrame({"k": k, "u": np.arange(2000)})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(df).repartition(6).orderBy("k", "u"),
        ignore_order=False)


def test_distributed_sort_bounded_residency():
    """Sorting more data than the device budget completes, with spill
    metrics proving residency stayed bounded."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec.spill import BufferCatalog
    rng = np.random.default_rng(8)
    n = 200_000
    df = pd.DataFrame({"k": rng.permutation(n).astype(np.int64),
                       "v": rng.normal(0, 1, n)})
    BufferCatalog.reset()
    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    cat = BufferCatalog.get()
    cat.device_budget = 1 << 20          # ~1 MiB: far below the dataset
    try:
        rows = (s.createDataFrame(df).repartition(4)
                .orderBy("k").collect())
        assert len(rows) == n
        ks = [r[0] for r in rows]
        assert ks == sorted(ks)
        assert cat.spilled_device_bytes > 0, \
            "expected device->host spill under the tiny budget"
    finally:
        BufferCatalog.reset()


def test_perfile_scan_partitions_drive_two_phase(tmp_path):
    """A multi-file PERFILE parquet scan is multi-partition, so the planner
    emits the distributed aggregate without an explicit repartition."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    df = _seeded(999)
    for i in range(3):
        pq.write_table(pa.Table.from_pandas(df.iloc[i::3], preserve_index=False),
                       tmp_path / f"part-{i}.parquet")
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.read.parquet(str(tmp_path))
                .groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("j").alias("c")))

    assert_tpu_and_cpu_equal(
        q, approx=1e-9,
        conf={"spark.rapids.tpu.sql.format.parquet.reader.type": "PERFILE"})
    plan = captured["s"].last_plan()
    assert _find(plan, TpuHashAggregateExec, lambda n: n.mode == "partial")


# -- adaptive partition coalescing (AQE analog; ref GpuCustomShuffleReader) --

def test_adaptive_coalesces_small_agg_partitions():
    """Tiny per-partition shuffle sizes collapse into fewer reduce
    partitions at runtime, and results stay correct."""
    from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(_seeded(400)).repartition(4)
                .groupBy("k").agg(F.sum("v").alias("sv"),
                                  F.count("*").alias("n")))

    assert_tpu_and_cpu_equal(q, approx=1e-9)
    exchanges = _find(captured["s"].last_plan(), TpuShuffleExchangeExec)
    adaptive = [e for e in exchanges if e.adaptive_ok]
    assert adaptive, "aggregate exchange should be adaptive"
    assert any(e.coalesced_to is not None and e.coalesced_to < e.num_partitions
               for e in adaptive), "tiny partitions should have coalesced"


def test_adaptive_disabled_keeps_partition_count():
    from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(_seeded(400)).repartition(4)
                .groupBy("k").agg(F.sum("v").alias("sv")))

    assert_tpu_and_cpu_equal(
        q, approx=1e-9,
        conf={"spark.rapids.tpu.sql.adaptive.enabled": "false"})
    for e in _find(captured["s"].last_plan(), TpuShuffleExchangeExec):
        assert e.coalesced_to is None or e.coalesced_to == e.num_partitions


def test_join_exchanges_never_adaptive():
    """Co-partitioned join sides must keep identical partition counts."""
    from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec
    left, right = _join_frames()
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(left)
                .join(s.createDataFrame(right), on=(col("a") == col("b")),
                      how="inner"))

    assert_tpu_and_cpu_equal(q, approx=1e-9, conf=_FORCE_SHUFFLE)
    from spark_rapids_tpu.plan.physical import TpuShuffledJoinExec
    joins = _find(captured["s"].last_plan(), TpuShuffledJoinExec)
    assert joins
    for e in _find(joins[0], TpuShuffleExchangeExec):
        assert not e.adaptive_ok


def test_filter_folds_into_aggregate():
    """A direct Filter child folds into the aggregate's fused update: no
    TpuFilterExec remains in the plan, and results match the oracle."""
    from spark_rapids_tpu.plan.physical import (TpuFilterExec,
                                                TpuHashAggregateExec)
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(_seeded())
                .filter(F.col("v") > 0)
                .groupBy("k").agg(F.sum("v").alias("sv"),
                                  F.count("*").alias("n"),
                                  F.avg("v").alias("av")))

    assert_tpu_and_cpu_equal(q, approx=1e-9)
    plan = captured["s"].last_plan()
    assert not _find(plan, TpuFilterExec), plan
    aggs = _find(plan, TpuHashAggregateExec,
                 lambda n: n.pre_filter is not None)
    assert aggs, plan


def test_folded_filter_global_agg_and_empty_result():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .filter(F.col("v") > 1e12)          # filters everything out
        .agg(F.count("*").alias("n"), F.sum("v").alias("sv")))


def test_folded_filter_two_phase():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded()).repartition(4)
        .filter(F.col("j") >= 0)
        .groupBy("k").agg(F.sum("v").alias("sv"),
                          F.count("v").alias("c")),
        approx=1e-9)
