"""End-to-end golden-compare tests: the integration-test ring analog
(SURVEY.md §4 ring 2: joins / hash_aggregate / sort / repart domains)."""

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit

from golden import assert_tpu_and_cpu_equal


def _seeded(n=200, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "i": [None if rng.random() < 0.1 else int(x)
              for x in rng.integers(-100, 100, n)],
        "j": [int(x) for x in rng.integers(0, 10, n)],
        "f": [None if rng.random() < 0.1 else float(x)
              for x in rng.normal(0, 50, n)],
        "s": [None if rng.random() < 0.1 else
              ["apple", "pear", "kiwi", "banana", "fig"][x]
              for x in rng.integers(0, 5, n)],
    }


def test_project_arithmetic():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .select((col("i") + 1).alias("a"), (col("i") * col("j")).alias("m"),
                (col("f") / 2).alias("h"), (-col("i")).alias("n")),
        approx=1e-12)


def test_filter_compound_predicate():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .filter((col("i") > 0) & (col("j") < 5) | col("s").isNull())
        .select("i", "j", "s"))


def test_conditional_exprs():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .select(F.when(col("i") > 0, lit("pos")).when(col("i") < 0, lit("neg"))
                .otherwise(lit("zero-or-null")).alias("sign"),
                F.coalesce(col("i"), col("j")).alias("c"),
                F.greatest(col("i"), col("j")).alias("g")))


def test_cast_matrix():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .select(col("i").cast("double").alias("d"),
                col("f").cast("int").alias("fi"),
                col("j").cast("string").alias("js"),
                col("i").cast("boolean").alias("ib")))


def test_groupby_aggregates():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .groupBy("j").agg(F.sum("i").alias("si"), F.count("i").alias("ci"),
                          F.avg("f").alias("af"), F.min("s").alias("mins"),
                          F.max("f").alias("maxf"),
                          F.count("*").alias("cstar")),
        approx=1e-9)


def test_groupby_string_key():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .groupBy("s").agg(F.sum("j").alias("sj")))


def test_reduction_no_keys():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .agg(F.sum("i").alias("si"), F.max("f").alias("mf"),
             F.count("*").alias("n")),
        approx=1e-9)


def test_sort_multi_key():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .orderBy(col("j").asc(), col("f").desc(), col("s").asc()),
        ignore_order=False, approx=1e-12)


def test_limit_after_sort():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .orderBy(col("i").asc_nulls_last()).limit(17),
        ignore_order=False)


def test_inner_join():
    def q(s):
        left = s.createDataFrame(_seeded(100, seed=1))
        right = s.createDataFrame(
            {"j": list(range(10)), "name": [f"grp{x}" for x in range(10)]})
        return left.join(right, on="j", how="inner").select("i", "j", "name")
    assert_tpu_and_cpu_equal(q)


def test_left_join_with_nulls():
    def q(s):
        left = s.createDataFrame({"k": [1, 2, None, 4], "v": [10, 20, 30, 40]})
        right = s.createDataFrame({"k": [1, 4, 5], "w": ["a", "b", "c"]})
        return left.join(right, on="k", how="left").select("k", "v", "w")
    assert_tpu_and_cpu_equal(q)


def test_semi_anti_join():
    def semi(s):
        left = s.createDataFrame(_seeded(80, 3))
        right = s.createDataFrame({"j": [1, 2, 3]})
        return left.join(right, on="j", how="left_semi").select("i", "j")
    assert_tpu_and_cpu_equal(semi)

    def anti(s):
        left = s.createDataFrame(_seeded(80, 3))
        right = s.createDataFrame({"j": [1, 2, 3]})
        return left.join(right, on="j", how="left_anti").select("i", "j")
    assert_tpu_and_cpu_equal(anti)


def test_full_outer_join():
    def q(s):
        left = s.createDataFrame({"k": [1, 2, 3], "v": [10, 20, 30]})
        right = s.createDataFrame({"k": [2, 3, 4], "w": [200, 300, 400]})
        return left.join(right, on=(col("k") == col("k")), how="full")
    # using explicit condition on same-named cols is ambiguous; use distinct names
    def q2(s):
        left = s.createDataFrame({"a": [1, 2, 3], "v": [10, 20, 30]})
        right = s.createDataFrame({"b": [2, 3, 4], "w": [200, 300, 400]})
        return left.join(right, on=(col("a") == col("b")), how="full")
    assert_tpu_and_cpu_equal(q2)


def test_union_distinct():
    def q(s):
        a = s.createDataFrame({"x": [1, 2, 3, 3]})
        b = s.createDataFrame({"x": [3, 4, None]})
        return a.union(b).distinct()
    assert_tpu_and_cpu_equal(q)


def test_string_functions():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .select(F.length(col("s")).alias("len"),
                F.substring(col("s"), 2, 3).alias("sub"),
                F.concat(col("s"), lit("-"), col("s")).alias("cc"),
                col("s").contains("an").alias("has"),
                col("s").like("%ea%").alias("lk"),
                F.trim(F.lpad(col("s"), 8, " ")).alias("tp")))


def test_expand_like_grouping():
    # distinct on computed column exercise
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .select((col("j") % 3).alias("g")).distinct())


def test_range():
    assert_tpu_and_cpu_equal(
        lambda s: s.range(0, 1000, 7).select((col("id") * 2).alias("x")),
        ignore_order=False)


def test_repartition_preserves_rows():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .repartition(4, col("j")).select("i", "j"))


def test_with_column_chain():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .withColumn("d", col("i") * 2)
        .withColumn("e", col("d") + col("j"))
        .drop("f")
        .filter(col("e").isNotNull()))


def test_count_action():
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.config("spark.rapids.tpu.sql.explain", "NONE").getOrCreate()
    df = s.createDataFrame({"x": [1, 2, None, 4]})
    assert df.count() == 4
    assert df.filter(col("x").isNotNull()).count() == 3


def test_full_outer_join_multi_partition_stream():
    """Full outer with a repartitioned stream side: unmatched build rows must
    be emitted exactly once globally, not once per stream partition."""
    def q(s):
        left = s.createDataFrame(
            {"a": [1, 2, 3, 4], "v": [10, 20, 30, 40]}).repartition(2, col("a"))
        right = s.createDataFrame({"b": [1, 9], "w": ["X", "Y"]})
        return left.join(right, on=(col("a") == col("b")), how="full")
    assert_tpu_and_cpu_equal(q)


def test_full_outer_join_empty_stream_side():
    def q(s):
        left = s.createDataFrame({"a": [1, 2], "v": [10, 20]}).filter(
            col("a") > 100)
        right = s.createDataFrame({"b": [2, 4], "w": ["X", "Y"]})
        return left.join(right, on=(col("a") == col("b")), how="full")
    assert_tpu_and_cpu_equal(q)


def test_join_null_keys_in_build_side():
    def q(s):
        left = s.createDataFrame({"a": [0, -5, 7], "v": [10, 20, 30]})
        right = s.createDataFrame(
            {"b": [None, -5, 0, 3], "w": [100, 200, 300, 400]})
        return left.join(right, on=(col("a") == col("b")), how="inner")
    assert_tpu_and_cpu_equal(q)


# -- DISTINCT aggregates (VERDICT r2 weak #1: countDistinct returned wrong
# answers on the TPU path; now planned as a two-level aggregate) -------------

def test_count_distinct_verdict_case():
    """The exact failing case from the round-2 verdict: (1,a),(1,a),(1,b),(2,c)
    must give count(DISTINCT v) of 2 for key 1, not 3."""
    import pyarrow as pa
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(pa.table({
            "k": [1, 1, 1, 2], "v": ["a", "a", "b", "c"]}))
        .groupBy("k").agg(F.countDistinct("v").alias("cd")))


def test_count_distinct_with_nulls():
    """NULLs in the distinct column are not counted (Spark count semantics)."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .groupBy("j").agg(F.countDistinct("s").alias("cd")))


def test_sum_distinct():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .groupBy("s").agg(F.sumDistinct("i").alias("sd")),
        approx=1e-12)


def test_distinct_mixed_with_plain_aggs():
    """DISTINCT alongside non-distinct aggregates: the non-distinct ones merge
    their per-(key, v) partials through the second level."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .groupBy("s").agg(F.countDistinct("j").alias("cd"),
                          F.sum("i").alias("si"),
                          F.avg("f").alias("af"),
                          F.count("i").alias("ci"),
                          F.min("i").alias("mi"),
                          F.max("f").alias("mf")),
        approx=1e-9)


def test_count_distinct_no_grouping():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .agg(F.countDistinct("j").alias("cd"),
             F.sum("i").alias("si")))


def test_distinct_with_agg_on_grouping_column():
    """A non-distinct aggregate over the GROUPING column alongside a DISTINCT:
    the leaf's child ColumnRef matches the grouping rewrite, so identity-based
    leaf matching must happen top-down (regression: bottom-up transform copied
    the leaf and skipped its merge rewrite, crashing at execution)."""
    import pyarrow as pa
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(pa.table({
            "k": [1, 1, 1, 2], "v": ["a", "a", "b", "c"]}))
        .groupBy("k").agg(F.countDistinct("v").alias("cd"),
                          F.sum("k").alias("sk")))


def test_multiple_distinct_columns_fall_back():
    """Two different DISTINCT column sets are not TPU-planned: the aggregate
    falls back to the CPU engine (and still answers correctly)."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .groupBy("s").agg(F.countDistinct("i").alias("ci"),
                          F.countDistinct("j").alias("cj")),
        expect_fallback=["Aggregate"])
