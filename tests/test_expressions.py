"""Expression eval tests: arithmetic/predicates/conditionals/cast/math with
Spark null semantics. Reference analog: ProjectExprSuite / CastOpSuite
(SURVEY.md §4 ring 1) asserting against known Spark behavior.
"""

import math

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, Scalar
from spark_rapids_tpu.ops import arithmetic as A
from spark_rapids_tpu.ops import cast as C
from spark_rapids_tpu.ops import conditionals as cond
from spark_rapids_tpu.ops import math_ops as M
from spark_rapids_tpu.ops import predicates as P
from spark_rapids_tpu.ops.expressions import col, lit


def _batch(**cols):
    return ColumnarBatch.from_pydict(cols)


def _eval(expr, batch):
    expr = expr.transform(
        lambda e: e.resolve(batch.schema) if hasattr(e, "resolve") else None)
    out = expr.eval(batch)
    if isinstance(out, Scalar):
        return out.value
    return out.to_pylist(batch.num_rows)


def test_add_nulls():
    b = _batch(x=[1, 2, None], y=[10, None, 30])
    assert _eval(A.Add(col("x"), col("y")), b) == [11, None, None]


def test_divide_by_zero_null():
    b = _batch(x=[10.0, 5.0, 1.0], y=[2.0, 0.0, 4.0])
    assert _eval(A.Divide(col("x"), col("y")), b) == [5.0, None, 0.25]


def test_remainder_and_pmod():
    b = _batch(x=[7, -7, 7], y=[3, 3, 0])
    assert _eval(A.Remainder(col("x"), col("y")), b) == [1, -1, None]
    assert _eval(A.Pmod(col("x"), col("y")), b) == [1, 2, None]


def test_unary_minus_abs():
    b = _batch(x=[1, -2, None])
    assert _eval(A.UnaryMinus(col("x")), b) == [-1, 2, None]
    assert _eval(A.Abs(col("x")), b) == [1, 2, None]


def test_comparisons():
    b = _batch(x=[1, 2, None], y=[2, 2, 2])
    assert _eval(P.LessThan(col("x"), col("y")), b) == [True, False, None]
    assert _eval(P.EqualTo(col("x"), col("y")), b) == [False, True, None]
    assert _eval(P.GreaterThanOrEqual(col("x"), col("y")), b) == [False, True, None]


def test_float_nan_comparison():
    nan = float("nan")
    b = _batch(x=[nan, 1.0, nan], y=[nan, nan, 1.0])
    assert _eval(P.EqualTo(col("x"), col("y")), b) == [True, False, False]
    # NaN is greater than everything
    assert _eval(P.GreaterThan(col("x"), col("y")), b) == [False, False, True]


def test_string_comparison():
    b = _batch(s=["apple", "pear", None])
    assert _eval(P.EqualTo(col("s"), lit("pear")), b) == [False, True, None]
    assert _eval(P.LessThan(col("s"), lit("banana")), b) == [True, False, None]


def test_kleene_and_or():
    b = _batch(x=[True, True, False, None], y=[None, False, None, None])
    assert _eval(P.And(col("x"), col("y")), b) == [None, False, False, None]
    assert _eval(P.Or(col("x"), col("y")), b) == [True, True, None, None]


def test_is_null_not():
    b = _batch(x=[1, None, 3])
    assert _eval(P.IsNull(col("x")), b) == [False, True, False]
    assert _eval(P.IsNotNull(col("x")), b) == [True, False, True]
    bb = _batch(p=[True, False, None])
    assert _eval(P.Not(col("p")), bb) == [False, True, None]


def test_in():
    b = _batch(x=[1, 2, 3, None])
    assert _eval(P.In(col("x"), [1, 3]), b) == [True, False, True, None]
    # NULL in list: non-matches become NULL
    assert _eval(P.In(col("x"), [1, None]), b) == [True, None, None, None]


def test_in_strings():
    b = _batch(s=["a", "b", None])
    assert _eval(P.In(col("s"), ["a", "c"]), b) == [True, False, None]


def test_if_case_when():
    b = _batch(x=[1, 5, None])
    e = cond.If(P.GreaterThan(col("x"), lit(2)), lit(100), lit(-100))
    assert _eval(e, b) == [-100, 100, -100]  # NULL predicate -> else branch
    cw = cond.CaseWhen(
        [(P.EqualTo(col("x"), lit(1)), lit("one")),
         (P.EqualTo(col("x"), lit(5)), lit("five"))], lit("other"))
    assert _eval(cw, b) == ["one", "five", "other"]


def test_coalesce_nvl_nullif():
    b = _batch(x=[None, 2, None], y=[1, 20, None])
    assert _eval(cond.Coalesce(col("x"), col("y")), b) == [1, 2, None]
    assert _eval(cond.NullIf(col("y"), lit(20)), b) == [1, None, None]


def test_least_greatest_skip_nulls():
    b = _batch(x=[1, None, None], y=[3, 5, None])
    assert _eval(cond.Greatest(col("x"), col("y")), b) == [3, 5, None]
    assert _eval(cond.Least(col("x"), col("y")), b) == [1, 5, None]


def test_cast_numeric():
    b = _batch(x=[1.9, -1.9, None])
    assert _eval(C.Cast(col("x"), dt.INT32), b) == [1, -1, None]
    b2 = _batch(i=[1, 0, None])
    assert _eval(C.Cast(col("i"), dt.BOOL), b2) == [True, False, None]


def test_cast_float_to_int_saturates():
    b = _batch(x=[1e300, -1e300, float("nan")])
    assert _eval(C.Cast(col("x"), dt.INT64), b) == [
        (1 << 63) - 1, -(1 << 63), 0]


def test_cast_int_narrowing_wraps():
    b = _batch(x=[300, -300, 127])
    out = _eval(C.Cast(col("x"), dt.INT8), b)
    assert out == [44, -44, 127]  # Java byte truncation


def test_cast_string_to_int():
    b = _batch(s=["42", " 7 ", "abc", None])
    assert _eval(C.Cast(col("s"), dt.INT32), b) == [42, 7, None, None]


def test_cast_int_to_string():
    b = _batch(x=[42, -1, None])
    assert _eval(C.Cast(col("x"), dt.STRING), b) == ["42", "-1", None]


def test_math_ops():
    b = _batch(x=[1.0, math.e, -1.0, None])
    out = _eval(M.Log(col("x")), b)
    assert out[0] == 0.0
    assert abs(out[1] - 1.0) < 1e-12
    assert out[2] is None  # log of negative -> NULL
    assert out[3] is None
    b2 = _batch(x=[4.0, 2.25])
    assert _eval(M.Sqrt(col("x")), b2) == [2.0, 1.5]


def test_floor_ceil_round():
    b = _batch(x=[1.5, -1.5, 2.5])
    assert _eval(M.Floor(col("x")), b) == [1, -2, 2]
    assert _eval(M.Ceil(col("x")), b) == [2, -1, 3]
    # Spark round = HALF_UP
    assert _eval(M.Round(col("x"), 0), b) == [2.0, -2.0, 3.0]


def test_pow():
    # approximate: XLA lowers pow to exp(y*log(x)) (reference marks pow
    # "incompat"/approximate_float for the same class of reason)
    b = _batch(x=[2.0, 3.0], y=[10.0, 0.0])
    out = _eval(M.Pow(col("x"), col("y")), b)
    assert out == pytest.approx([1024.0, 1.0], rel=1e-12)


def test_scalar_folding():
    b = _batch(x=[1])
    assert _eval(A.Add(lit(2), lit(3)), b) == 5
    assert _eval(A.Divide(lit(1.0), lit(0.0)), b) is None


# -- stateful expressions: Rand / monotonically_increasing_id ----------------
# (VERDICT r2 weak #4: Rand replayed the same sequence every batch)

def _two_batch_project(exprs_fn, n_rows=64, batch_rows=16, num_partitions=1):
    """Run a projection over a multi-batch partition and collect all rows."""
    import pyarrow as pa
    from spark_rapids_tpu.plan import physical as ph
    from spark_rapids_tpu.ops import expressions as ex
    table = pa.table({"x": list(range(n_rows))})
    scan = ph.TpuLocalScanExec(
        table, _schema_of(table), batch_rows=batch_rows,
        num_partitions=num_partitions)
    proj = ph.TpuProjectExec(scan, exprs_fn())
    rows = []
    for part in proj.execute():
        for b in part:
            d = b.to_pydict()
            rows.extend(zip(*[d[n] for n in b.schema.names()]))
    return rows


def _schema_of(table):
    from spark_rapids_tpu.columnar import dtypes as dt
    return dt.Schema([dt.Field(n, dt.from_arrow(t))
                      for n, t in zip(table.schema.names, table.schema.types)])


def test_rand_no_per_batch_replay():
    from spark_rapids_tpu.ops import hashing as hs
    from spark_rapids_tpu.ops import expressions as ex
    rows = _two_batch_project(
        lambda: [ex.Alias(hs.Rand(seed=42), "r")], n_rows=64, batch_rows=16)
    vals = [r[0] for r in rows]
    # 4 batches of 16: the old code repeated the identical 16 values 4x
    assert len(set(vals)) == len(vals), "rand values replay across batches"
    assert all(0.0 <= v < 1.0 for v in vals)


def test_rand_deterministic_per_seed_and_partition():
    from spark_rapids_tpu.ops import hashing as hs
    from spark_rapids_tpu.ops import expressions as ex
    a = _two_batch_project(lambda: [ex.Alias(hs.Rand(seed=7), "r")])
    b = _two_batch_project(lambda: [ex.Alias(hs.Rand(seed=7), "r")])
    assert a == b, "same seed must reproduce the same stream"
    c = _two_batch_project(lambda: [ex.Alias(hs.Rand(seed=8), "r")])
    assert a != c
    # different partitions draw different streams
    rows = _two_batch_project(lambda: [ex.Alias(hs.Rand(seed=7), "r")],
                              n_rows=64, batch_rows=32, num_partitions=2)
    vals = [r[0] for r in rows]
    assert len(set(vals)) == len(vals)


def test_monotonically_increasing_id_advances_across_batches():
    from spark_rapids_tpu.ops import hashing as hs
    from spark_rapids_tpu.ops import expressions as ex
    rows = _two_batch_project(
        lambda: [ex.Alias(hs.MonotonicallyIncreasingID(), "id")],
        n_rows=48, batch_rows=16)
    vals = [r[0] for r in rows]
    assert vals == list(range(48)), vals
    # two partitions: ids disjoint, offset by the 1<<33 partition stride
    rows = _two_batch_project(
        lambda: [ex.Alias(hs.MonotonicallyIncreasingID(), "id")],
        n_rows=64, batch_rows=16, num_partitions=2)
    vals = sorted(r[0] for r in rows)
    assert vals[:32] == list(range(32))
    assert vals[32:] == [(1 << 33) + i for i in range(32)]
