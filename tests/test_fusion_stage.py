"""Whole-stage fusion units (ISSUE 11): the stage compiler
(plan/stage_compiler.py), the q6-shaped one-program-per-stage invariant,
batch-size autotuning, and the streaming-scan prefetch discipline."""

import threading

import numpy as np
import pytest

from spark_rapids_tpu.api.functions import col, lit
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession


def _session(extra=None):
    conf = {"spark.rapids.tpu.sql.explain": "NONE"}
    conf.update(extra or {})
    return TpuSession.builder.config(conf).getOrCreate()


def _rows(batch):
    return sorted(batch.fetch_to_host().rows())


def _df(session, n=20_000, seed=7):
    rng = np.random.default_rng(seed)
    return session.createDataFrame({
        "k": [int(x) for x in rng.integers(0, 50, n)],
        "a": [float(x) for x in rng.normal(0, 10, n)],
        "b": [int(x) for x in rng.integers(0, 100, n)]})


def _chain_query(df):
    return (df.select((col("a") * lit(2.0)).alias("a2"), col("b"), col("k"))
            .filter(col("a2") > lit(0.0))
            .select((col("a2") + col("b")).alias("s"), col("k"))
            .filter(col("k") < lit(40)))


# ---------------------------------------------------------------------------
# chain semantics + the fused exec
# ---------------------------------------------------------------------------

def test_chain_collapses_to_one_whole_stage_exec():
    from spark_rapids_tpu.plan.stage_compiler import TpuWholeStageExec
    session = _session()
    q = _chain_query(_df(session))
    got = _rows(q.collect_batch())
    plan = session.last_plan()
    stages = [n for n in _walk(plan) if isinstance(n, TpuWholeStageExec)]
    assert len(stages) == 1, plan
    assert stages[0].members == ["TpuProjectExec", "TpuFilterExec",
                                 "TpuProjectExec", "TpuFilterExec"]
    assert not stages[0].broken
    # parity against the per-op path
    session.conf.set("spark.rapids.tpu.sql.fusion.wholeStage", "false")
    try:
        assert _rows(q.collect_batch()) == got
        plan_off = session.last_plan()
        assert not [n for n in _walk(plan_off)
                    if isinstance(n, TpuWholeStageExec)]
    finally:
        session.conf.set("spark.rapids.tpu.sql.fusion.wholeStage", "true")


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


def test_stage_program_compiles_once_and_classifies():
    """The stage program rides the _fused_fn funnel: ONE compile for the
    whole chain (kernel family 'stage'), classified cold/disk like every
    other kernel family, and a repeat run compiles nothing."""
    from spark_rapids_tpu.analysis import recompile
    session = _session()
    df = _df(session, seed=11)
    # structurally unique literals: the global fused cache is process-wide,
    # and an expression chain another test already compiled would hit it
    q = (df.select((col("a") * lit(2.125)).alias("a2"), col("b"))
         .filter(col("a2") > lit(0.375))
         .select((col("a2") + col("b") * lit(3.0)).alias("s"), col("b"))
         .filter(col("b") < lit(47)))
    base = recompile.snapshot()
    q.collect_batch().fetch_to_host()
    d = recompile.delta(base)
    stage = {k: v for k, v in d.items() if k.startswith("stage")}
    assert stage, d
    (fam, ent), = stage.items()
    assert ent["compiles"] == 1, ent
    assert ent["coldCompiles"] + ent["diskHits"] == 1, ent
    # no separate per-op project/filter programs were built for the chain
    assert not any(k.startswith("fused_project") or
                   k.startswith("fused_filter") or
                   k.startswith("project") or k.startswith("filter")
                   for k in d), d
    snap = recompile.snapshot()
    q.collect_batch().fetch_to_host()
    rd = recompile.delta(snap)
    assert not any(v.get("compiles") for v in rd.values()), rd


def test_q6_shaped_stage_one_program_o1_syncs():
    """The q6 shape — scan -> filter -> project -> aggregate — folds the
    whole chain into the aggregate's update program: exactly ONE device
    program family per batch and O(1) host syncs per partition even when
    the scan streams many batches."""
    from spark_rapids_tpu.analysis import recompile
    from spark_rapids_tpu.plan import physical as ph
    session = _session({
        # pin small batches so the partition streams 8+ of them
        "spark.rapids.tpu.sql.reader.batchSizeRows": 1 << 14})
    df = _df(session, n=140_000, seed=13)
    q = (df.filter((col("a") > lit(-5.0)) & (col("b") < lit(90)))
         .select((col("a") * col("b")).alias("v"))
         .agg(F.sum(col("v")).alias("s")))
    base = recompile.snapshot()
    res = q.collect_batch().fetch_to_host()
    assert res.num_rows == 1
    plan = session.last_plan()
    aggs = [n for n in _walk(plan)
            if isinstance(n, ph.TpuHashAggregateExec)]
    assert aggs and aggs[0].pre_stage is not None
    assert len(aggs[0].pre_stage.steps) == 2          # filter + project
    assert getattr(aggs[0], "_fusion_members", []) == [
        "TpuFilterExec", "TpuProjectExec"]
    # chain members are GONE from the executed tree
    assert not [n for n in _walk(plan)
                if isinstance(n, (ph.TpuFilterExec, ph.TpuProjectExec))]
    d = recompile.delta(base)
    # the whole stage lowered to the agg's OWN update family: exactly one
    # program per batch shape (donate variants are distinct shapes), and
    # NO separate filter/project/stage programs were built for the chain
    upd = [k for k in d if k.startswith("agg/update") and "pre_stage" in k]
    assert len(upd) == 1, d
    assert d[upd[0]]["compiles"] == d[upd[0]]["distinctShapes"], d
    assert not any(k.startswith(("stage", "fused_project", "fused_filter",
                                 "project", "filter")) for k in d), d
    # O(1) syncs for the whole partition (many batches): the count sync
    # of the final one-row fetch plus at most a couple of boundary syncs
    sync = session.last_query_metrics()["sync"]
    assert sync.get("hostSyncs", 0) <= 4, sync
    # oracle
    import pandas as pd
    h = df.collect_batch().fetch_to_host().to_pandas()
    sub = h[(h.a > -5.0) & (h.b < 90)]
    expect = float((sub.a * sub.b).sum())
    got = float(res.to_pydict()["s"][0])
    assert abs(got - expect) <= 1e-6 * max(1.0, abs(expect))


def test_fusion_decline_reason_surfaces_and_stays_correct():
    """A stateful expression declines stage fusion with a per-node reason
    in EXPLAIN ANALYZE, and the per-op path still answers correctly."""
    session = _session()
    df = _df(session, n=2_000)
    q = (df.select((F.rand(42) * lit(0.0) + col("a")).alias("r"), col("b"))
         .filter(col("b") < lit(50)))
    out = q.collect_batch().fetch_to_host()
    assert out.num_rows > 0
    txt = session.explain_analyze()
    assert "fusion declined" in txt, txt
    assert "stateful expression" in txt, txt


def test_explain_analyze_shows_stage_membership():
    session = _session()
    q = _chain_query(_df(session, seed=23))
    q.collect_batch().fetch_to_host()
    txt = session.explain_analyze()
    assert "fused stage #" in txt, txt
    assert "compiled into one program" in txt, txt
    # the q6 shape shows the agg-folded membership too
    q2 = (_df(session, seed=29).filter(col("a") > lit(0.0))
          .select((col("a") + lit(1.0)).alias("v"))
          .agg(F.sum(col("v")).alias("s")))
    q2.collect_batch().fetch_to_host()
    txt2 = session.explain_analyze()
    assert "folded into this aggregate" in txt2, txt2


def test_scalar_predicate_falls_back_to_per_op():
    """A constant predicate inside a chain breaks the trace and degrades
    permanently to the eager per-op path — same results."""
    session = _session()
    df = _df(session, n=4_000, seed=31)
    q = (df.select((col("a") * lit(3.0)).alias("x"), col("b"))
         .filter(lit(True))
         .filter(col("b") >= lit(10)))
    on_rows = _rows(q.collect_batch())
    session.conf.set("spark.rapids.tpu.sql.fusion.wholeStage", "false")
    try:
        assert _rows(q.collect_batch()) == on_rows
    finally:
        session.conf.set("spark.rapids.tpu.sql.fusion.wholeStage", "true")


# ---------------------------------------------------------------------------
# batch-size autotuning
# ---------------------------------------------------------------------------

def test_tuned_batch_rows_properties():
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.plan import stage_compiler as sc
    schema = dt.Schema([dt.Field("a", dt.FLOAT64), dt.Field("b", dt.INT64)])
    sc.reset_tuning_cache()
    conf = cfg.TpuConf()
    rows = sc.tuned_batch_rows(conf, schema)
    assert rows >= 1 << 14
    assert rows & (rows - 1) == 0, rows          # power of two
    assert rows <= int(conf.get(cfg.BATCH_AUTOTUNE_MAX_ROWS))
    # deterministic across calls (the recompile gate needs stable shapes)
    assert sc.tuned_batch_rows(conf, schema) == rows
    # an explicit reader.batchSizeRows stays a hard cap
    sc.reset_tuning_cache()
    pinned = cfg.TpuConf({cfg.MAX_READER_BATCH_SIZE_ROWS.key: 1 << 15})
    assert sc.tuned_batch_rows(pinned, schema) <= 1 << 15
    # autotune off reproduces the legacy bytes-derived target
    sc.reset_tuning_cache()
    off = cfg.TpuConf({cfg.BATCH_AUTOTUNE.key: "false"})
    legacy = sc.tuned_batch_rows(off, schema)
    row_bytes = sum((f.dtype.byte_width or 32) + 1 for f in schema)
    assert legacy == max(
        1 << 14, min(int(off.batch_size_bytes) // row_bytes,
                     int(off.get(cfg.MAX_READER_BATCH_SIZE_ROWS))))
    sc.reset_tuning_cache()


def test_tuned_batch_rows_shrinks_under_pressure():
    """A nearly-exhausted device watermark shrinks the pick (never below
    the floor) — the 'largest SAFE batch' half of the contract."""
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.plan import stage_compiler as sc
    from spark_rapids_tpu.service.telemetry import watermark
    schema = dt.Schema([dt.Field("a", dt.FLOAT64)])
    conf = cfg.TpuConf()
    sc.reset_tuning_cache()
    free = sc.tuned_batch_rows(conf, schema)
    wm = watermark("device")
    before = wm.current
    try:
        sc.reset_tuning_cache()
        wm.update(sc._device_budget_bytes())      # budget fully in use
        pressed = sc.tuned_batch_rows(conf, schema)
    finally:
        wm.update(before)
        sc.reset_tuning_cache()
    assert pressed <= free
    assert pressed >= 1 << 14


# ---------------------------------------------------------------------------
# streaming scan / prefetch discipline
# ---------------------------------------------------------------------------

def test_ordered_prefetch_order_error_and_naming():
    from spark_rapids_tpu.exec.tasks import ordered_prefetch
    seen_names = set()

    def fn(i):
        seen_names.add(threading.current_thread().name)
        return i * i

    out = list(ordered_prefetch(range(40), fn, threads=3, depth=2,
                                name="tpu-scan-prefetch"))
    assert out == [i * i for i in range(40)]
    assert seen_names and all(n.startswith("tpu-scan-prefetch-")
                              for n in seen_names), seen_names

    def boom(i):
        if i == 5:
            raise RuntimeError("decode failed")
        return i

    with pytest.raises(RuntimeError, match="decode failed"):
        list(ordered_prefetch(range(10), boom, threads=2))


def test_ordered_prefetch_bounded_join_on_early_close():
    """Closing the consumer early must stop and join the workers (bounded
    join on shutdown — the transport-thread discipline)."""
    from spark_rapids_tpu.exec.tasks import ordered_prefetch
    gen = ordered_prefetch(range(100), lambda i: i, threads=2, depth=2,
                           name="tpu-scan-prefetch")
    assert next(iter(gen)) == 0
    gen.close()
    import time
    deadline = time.time() + 6.0
    while time.time() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name.startswith("tpu-scan-prefetch-")]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, alive


def test_abandoned_scan_partition_returns_staging_windows(tmp_path):
    """A partition drain abandoned mid-stream (limit-style early exit)
    must hand every pinned staging-arena window back — leaked windows
    would permanently shrink the process-global arena."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.io import scan as scan_mod
    rng = np.random.default_rng(17)
    for i in range(4):
        tbl = pa.table({"x": rng.integers(0, 100, 4000),
                        "y": rng.normal(0, 1, 4000)})
        pq.write_table(tbl, str(tmp_path / f"f{i}.parquet"))
    session = _session({
        "spark.rapids.tpu.sql.format.parquet.reader.type": "MULTITHREADED"})
    from spark_rapids_tpu.plan import logical as lp
    from spark_rapids_tpu.io.scan import TpuFileScanExec
    from spark_rapids_tpu.columnar import dtypes as dt
    plan = lp.FileScan("parquet", [str(tmp_path)],
                       dt.Schema([dt.Field("x", dt.INT64),
                                  dt.Field("y", dt.FLOAT64)]))
    exec_ = TpuFileScanExec(plan, session.conf)
    part = exec_.execute()[0]
    next(part)                      # one batch uploaded...
    part.close()                    # ...then the consumer walks away
    staging = scan_mod._STAGING
    if staging is not None:         # arena was used: must be fully freed
        assert staging.allocator.allocated_bytes == 0, \
            staging.allocator.allocated_bytes


def test_streaming_scan_strategies_agree(tmp_path):
    """MULTITHREADED (streamed, prefetch pool) == COALESCING == PERFILE on
    a multi-file parquet dataset, and the prefetch thread count follows
    scan.prefetchThreads."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(5)
    for i in range(6):
        tbl = pa.table({"x": rng.integers(0, 1000, 500),
                        "y": rng.normal(0, 1, 500)})
        pq.write_table(tbl, str(tmp_path / f"part-{i}.parquet"))
    got = {}
    for strategy in ("MULTITHREADED", "COALESCING", "PERFILE"):
        session = _session({
            "spark.rapids.tpu.sql.format.parquet.reader.type": strategy,
            "spark.rapids.tpu.sql.scan.prefetchThreads": 3})
        df = session.read.parquet(str(tmp_path))
        got[strategy] = sorted(df.collect_batch().fetch_to_host().rows())
        assert len(got[strategy]) == 3000
    assert got["MULTITHREADED"] == got["COALESCING"] == got["PERFILE"]
