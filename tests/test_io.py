"""IO tests: parquet/csv/orc round trips through scan strategies + writers.

Reference analog: integration_tests parquet_test / csv_test / orc_test
round-trip patterns (SURVEY.md §4 ring 2).
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api.functions import col


@pytest.fixture
def session():
    return TpuSession.builder.config(
        "spark.rapids.tpu.sql.explain", "NONE").getOrCreate()


def _sample_table(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "i": pa.array([None if rng.random() < 0.1 else int(x)
                       for x in rng.integers(0, 100, n)], type=pa.int64()),
        "f": pa.array(rng.normal(size=n), type=pa.float64()),
        "s": pa.array([f"row-{i}" if i % 7 else None for i in range(n)]),
    })


def test_parquet_roundtrip_perfile(session, tmp_path):
    t = _sample_table()
    path = str(tmp_path / "data.parquet")
    pq.write_table(t, path)
    for reader in ("PERFILE", "COALESCING", "MULTITHREADED"):
        s = TpuSession.builder.config({
            "spark.rapids.tpu.sql.explain": "NONE",
            "spark.rapids.tpu.sql.format.parquet.reader.type": reader,
        }).getOrCreate()
        df = s.read.parquet(path)
        got = df.to_arrow()
        assert got.equals(t), f"reader {reader} mismatch"


def test_parquet_multifile(session, tmp_path):
    tables = [_sample_table(50, seed=i) for i in range(4)]
    for i, t in enumerate(tables):
        pq.write_table(t, str(tmp_path / f"part-{i}.parquet"))
    df = session.read.parquet(str(tmp_path))
    assert df.count() == 200


def test_parquet_write_read(session, tmp_path):
    df = session.createDataFrame(
        {"a": [1, 2, 3], "b": ["x", None, "z"]})
    out = str(tmp_path / "out")
    df.write.parquet(out)
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    back = session.read.parquet(out)
    assert sorted(back.collect()) == sorted(df.collect())


def test_parquet_partitioned_write(session, tmp_path):
    df = session.createDataFrame(
        {"k": [1, 1, 2, 2], "v": [10, 20, 30, 40]})
    out = str(tmp_path / "p")
    df.write.partitionBy("k").parquet(out)
    assert os.path.isdir(os.path.join(out, "k=1"))
    assert os.path.isdir(os.path.join(out, "k=2"))
    import pyarrow.parquet as pq2
    t1 = pq2.read_table(os.path.join(out, "k=1"))
    assert sorted(t1.column("v").to_pylist()) == [10, 20]


def test_csv_roundtrip(session, tmp_path):
    df = session.createDataFrame({"a": [1, 2, 3], "b": [1.5, 2.5, None]})
    out = str(tmp_path / "c")
    df.write.option("header", "true").csv(out)
    back = session.read.option("header", "true").csv(out)
    rows = sorted(back.collect())
    assert rows[0][0] == 1 and rows[2][1] is None


def test_orc_roundtrip(session, tmp_path):
    df = session.createDataFrame({"a": [1, 2, None], "s": ["p", "q", "r"]})
    out = str(tmp_path / "o")
    df.write.orc(out)
    back = session.read.orc(out)
    assert sorted(back.collect(), key=lambda r: (r[0] is None, r[0] or 0)) == \
        sorted(df.collect(), key=lambda r: (r[0] is None, r[0] or 0))


def test_parquet_predicate_pushdown_prunes(session, tmp_path):
    # row-group pruning: write with small row groups, filter on sorted column
    t = pa.table({"x": pa.array(range(10000), type=pa.int64())})
    path = str(tmp_path / "big.parquet")
    pq.write_table(t, path, row_group_size=1000)
    df = session.read.parquet(path).filter(col("x") >= 9500)
    # scan picks up the pushed filter through the logical plan
    from spark_rapids_tpu.plan import logical as lp
    plan = df._analyzed()
    # push filters into the scan (planner optimization is scan-side here)
    assert df.count() == 500


def test_write_modes(session, tmp_path):
    df = session.createDataFrame({"a": [1]})
    out = str(tmp_path / "m")
    df.write.parquet(out)
    with pytest.raises(FileExistsError):
        df.write.parquet(out)
    df.write.mode("overwrite").parquet(out)
    df.write.mode("ignore").parquet(out)
    assert session.read.parquet(out).count() == 1


# -- hive partition values on read (ref:
# ColumnarPartitionReaderWithPartitionValues.scala, GpuParquetScan.scala:749) --

def test_partitioned_write_read_roundtrip(tmp_path):
    """write partitioned -> read back: the k=v dir segments come back as
    typed columns, including the NULL partition."""
    import pandas as pd
    from golden import assert_tpu_and_cpu_equal
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    df = pd.DataFrame({
        "k": [1, 1, 2, 2, 2, 3],
        "region": ["east", "west", "east", None, "west", "east"],
        "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]})
    out = str(tmp_path / "part_out")
    s.createDataFrame(df).write.partitionBy("k", "region").parquet(out)

    def q(sess):
        return sess.read.parquet(out)

    rows = assert_tpu_and_cpu_equal(q)
    assert len(rows) == 6
    got = sorted((r for r in rows), key=lambda r: (r[1] is None, str(r)))
    # partition cols appended after data cols: schema is (v, k, region)
    sch = {f.name: f.dtype.name
           for f in q(s)._analyzed().schema}
    assert sch["k"] == "bigint" and sch["region"] == "string"
    assert any(r[2] is None for r in rows)          # NULL partition survives


def test_partition_value_type_inference(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.io import partition_schema
    for d, fname in (("p=1.5/q=x", "a.parquet"), ("p=2/q=y", "b.parquet")):
        (tmp_path / d).mkdir(parents=True)
        pq.write_table(pa.table({"v": [1]}), tmp_path / d / fname)
    from spark_rapids_tpu.io import expand_paths
    files = expand_paths([str(tmp_path)])
    ps = partition_schema(files, [str(tmp_path)])
    types = {f.name: f.dtype.name for f in ps}
    assert types == {"p": "double", "q": "string"}
