"""Sort-merge join kernel tests against pandas merge oracles.

Reference analog: join integration tests + GpuHashJoin tag/remap behavior
(SURVEY.md §2.4, §4).
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Column, bucket
from spark_rapids_tpu.ops.joins import (cross_join_gather, join_gather,
                                        join_match, unmatched_build_gather)


def _col(vals, dtype):
    return Column.from_pylist(vals, dtype)


def _join(build_keys, build_cols, n_build, stream_keys, stream_cols, n_stream,
          how="inner"):
    m = join_match(build_keys, n_build, stream_keys, n_stream,
                   stream_keys[0].capacity)
    total = int(m.total_pairs)
    if how == "left":
        total = int(np.sum(np.maximum(np.asarray(m.count)[:n_stream], 1)))
    cap = bucket(max(total, 1))
    s_out, b_out, cnt = join_gather(m, stream_cols, build_cols, cap, how,
                                    n_stream=n_stream)
    n = int(cnt)
    return ([c.to_pylist(n) for c in s_out], [c.to_pylist(n) for c in b_out], m)


def _rows(*cols):
    return sorted(zip(*cols), key=lambda r: tuple(
        (x is None, x if x is not None else 0) for x in r))


def test_inner_join_basic():
    bk = _col([1, 2, 2, 3], dt.INT64)
    bv = _col(["b1", "b2a", "b2b", "b3"], dt.STRING)
    sk = _col([2, 1, 4, 2], dt.INT64)
    sv = _col([100, 200, 300, 400], dt.INT64)
    s_out, b_out, m = _join([bk], [bv], 4, [sk], [sk, sv], 4, "inner")
    got = _rows(s_out[1], b_out[0])
    assert got == _rows([100, 100, 200, 400, 400], ["b2a", "b2b", "b1", "b2a", "b2b"])


def test_null_keys_never_match():
    bk = _col([1, None], dt.INT64)
    bv = _col([10, 20], dt.INT64)
    sk = _col([1, None], dt.INT64)
    sv = _col([100, 200], dt.INT64)
    s_out, b_out, _ = _join([bk], [bv], 2, [sk], [sv], 2, "inner")
    assert s_out[0] == [100]
    assert b_out[0] == [10]


def test_left_join():
    bk = _col([1, 2], dt.INT64)
    bv = _col([10, 20], dt.INT64)
    sk = _col([2, 5, None], dt.INT64)
    sv = _col([100, 200, 300], dt.INT64)
    s_out, b_out, _ = _join([bk], [bv], 2, [sk], [sk, sv], 3, "left")
    got = _rows(s_out[1], b_out[0])
    assert got == _rows([100, 200, 300], [20, None, None])


def test_semi_anti_join():
    bk = _col([1, 2, 2], dt.INT64)
    sk = _col([2, 3, None, 1], dt.INT64)
    sv = _col([100, 200, 300, 400], dt.INT64)
    m = join_match([bk], 3, [sk], 4, sk.capacity)
    s_out, _, cnt = join_gather(m, [sv], [], 128, "left_semi", n_stream=4)
    assert sorted(s_out[0].to_pylist(int(cnt))) == [100, 400]
    s_out, _, cnt = join_gather(m, [sv], [], 128, "left_anti", n_stream=4)
    assert sorted(s_out[0].to_pylist(int(cnt))) == [200, 300]


def test_full_outer_pieces():
    bk = _col([1, 9, None], dt.INT64)
    bv = _col([10, 90, 99], dt.INT64)
    sk = _col([1, 5], dt.INT64)
    m = join_match([bk], 3, [sk], 2, sk.capacity)
    un, cnt = unmatched_build_gather(m, [bv], 3)
    # build rows 9 and NULL-key row are unmatched
    assert sorted(un[0].to_pylist(int(cnt))) == [90, 99]


def test_string_key_join():
    bk = _col(["apple", "pear", None], dt.STRING)
    bv = _col([1, 2, 3], dt.INT64)
    sk = _col(["pear", "apple", "kiwi", None], dt.STRING)
    sv = _col([10, 20, 30, 40], dt.INT64)
    s_out, b_out, _ = _join([bk], [bv], 3, [sk], [sv], 4, "inner")
    got = _rows(s_out[0], b_out[0])
    assert got == _rows([10, 20], [2, 1])


def test_multi_key_join():
    bk1 = _col([1, 1, 2], dt.INT64)
    bk2 = _col(["x", "y", "x"], dt.STRING)
    bv = _col([11, 12, 21], dt.INT64)
    sk1 = _col([1, 2, 1], dt.INT64)
    sk2 = _col(["y", "x", "z"], dt.STRING)
    sv = _col([100, 200, 300], dt.INT64)
    s_out, b_out, _ = _join([bk1, bk2], [bv], 3, [sk1, sk2], [sv], 3, "inner")
    got = _rows(s_out[0], b_out[0])
    assert got == _rows([100, 200], [12, 21])


def test_float_key_join_nan_matches_nan():
    nan = float("nan")
    bk = _col([1.0, nan], dt.FLOAT64)
    bv = _col([1, 2], dt.INT64)
    sk = _col([nan, 1.0, 2.0], dt.FLOAT64)
    sv = _col([10, 20, 30], dt.INT64)
    s_out, b_out, _ = _join([bk], [bv], 2, [sk], [sv], 3, "inner")
    got = _rows(s_out[0], b_out[0])
    # Spark: NaN == NaN in joins
    assert got == _rows([10, 20], [2, 1])


def test_cross_join():
    lk = _col([1, 2], dt.INT64)
    rk = _col([10, 20, 30], dt.INT64)
    l_out, r_out, cnt = cross_join_gather([lk], 2, [rk], 3, 128)
    n = int(cnt)
    assert n == 6
    pairs = sorted(zip(l_out[0].to_pylist(n), r_out[0].to_pylist(n)))
    assert pairs == [(1, 10), (1, 20), (1, 30), (2, 10), (2, 20), (2, 30)]


def test_join_random_vs_pandas():
    rng = np.random.default_rng(7)
    n_b, n_s = 200, 300
    bk = rng.integers(0, 60, n_b)
    bv = rng.integers(0, 1000, n_b)
    sk = rng.integers(0, 80, n_s)
    sv = rng.integers(0, 1000, n_s)
    bkc, bvc = _col(list(bk), dt.INT64), _col(list(bv), dt.INT64)
    skc, svc = _col(list(sk), dt.INT64), _col(list(sv), dt.INT64)

    for how in ("inner", "left"):
        s_out, b_out, _ = _join([bkc], [bvc], n_b, [skc], [skc, svc], n_s, how)
        got = _rows(s_out[0], s_out[1], b_out[0])
        df_b = pd.DataFrame({"k": bk, "bv": bv})
        df_s = pd.DataFrame({"k": sk, "sv": sv})
        merged = df_s.merge(df_b, on="k", how=how)
        exp = _rows(list(merged["k"]), list(merged["sv"]),
                    [None if pd.isna(x) else int(x) for x in merged["bv"]])
        assert got == exp


def test_null_build_keys_all_types():
    """NULL build keys must never match (they sort first with zeroed data
    words — the search must rank them below every usable probe key)."""
    bk = _col([None, -5, 0, 3], dt.INT64)
    bv = _col([100, 200, 300, 400], dt.INT64)
    sk = _col([0, -5], dt.INT64)
    sv = _col([10, 20], dt.INT64)
    s_out, b_out, m = _join([bk], [bv], 4, [sk], [sv], 2, "inner")
    got = _rows(s_out[0], b_out[0])
    assert got == _rows([10, 20], [300, 200])

    # semi/anti against build side containing NULL keys
    s_out, _, _ = _join([bk], [bv], 4, [sk], [sv], 2, "left_semi")
    assert sorted(s_out[0]) == [10, 20]
    sk2 = _col([7, -5, None], dt.INT64)
    sv2 = _col([1, 2, 3], dt.INT64)
    s_out, _, _ = _join([bk], [bv], 4, [sk2], [sv2], 3, "left_anti")
    assert sorted(s_out[0]) == [1, 3]


def test_null_build_keys_left_and_unmatched():
    bk = _col([None, 2], dt.INT64)
    bv = _col([111, 222], dt.INT64)
    sk = _col([2, 9], dt.INT64)
    sv = _col([10, 20], dt.INT64)
    s_out, b_out, m = _join([bk], [bv], 2, [sk], [sv], 2, "left")
    got = _rows(s_out[0], b_out[0])
    assert got == _rows([10, 20], [222, None])
    # full-outer composition: the NULL-key build row is unmatched
    un_cols, ucnt = unmatched_build_gather(m, [bv], 2)
    assert un_cols[0].to_pylist(int(ucnt)) == [111]


def test_float64_keys_full_precision():
    """f64 keys differing only beyond f32 precision must not join."""
    a = 1.0
    b = 1.0 + 2.0 ** -40          # == a when rounded to f32
    bk = _col([a, b], dt.FLOAT64)
    bv = _col([1, 2], dt.INT64)
    sk = _col([a], dt.FLOAT64)
    sv = _col([10], dt.INT64)
    s_out, b_out, _ = _join([bk], [bv], 2, [sk], [sv], 1, "inner")
    assert b_out[0] == [1]


def test_negative_zero_joins_positive_zero():
    bk = _col([-0.0, 5.0], dt.FLOAT64)
    bv = _col([1, 2], dt.INT64)
    sk = _col([0.0], dt.FLOAT64)
    sv = _col([10], dt.INT64)
    s_out, b_out, _ = _join([bk], [bv], 2, [sk], [sv], 1, "inner")
    assert b_out[0] == [1]


def test_string_keys_different_widths():
    """Build/stream string key columns with different padded byte widths."""
    bk = _col(["apple", "fig"], dt.STRING)
    bv = _col([1, 2], dt.INT64)
    sk = _col(["a-much-longer-string-key-here", "apple", "fig"], dt.STRING)
    sv = _col([10, 20, 30], dt.INT64)
    assert bk.data.shape[1] != sk.data.shape[1]
    s_out, b_out, _ = _join([bk], [bv], 2, [sk], [sv], 3, "inner")
    got = _rows(s_out[0], b_out[0])
    assert got == _rows([20, 30], [1, 2])


def test_runtime_broadcast_switch():
    """AQE join-strategy switch: a shuffled join whose build side turns
    out SMALL at runtime joins via a materialized broadcast batch and
    skips the stream-side shuffle (runtimeBroadcastJoins metric set);
    a large build side stays co-partitioned."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.plan.physical import TpuShuffledJoinExec

    def find(node, klass):
        out = [node] if isinstance(node, klass) else []
        for c in node.children:
            out.extend(find(c, klass))
        return out

    s = TpuSession.builder.config({
        # estimates below force the SHUFFLED plan; runtime sizes overrule
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "1",
        "spark.rapids.tpu.sql.adaptive.enabled": "true",
        "spark.rapids.tpu.sql.explain": "NONE",
    }).getOrCreate()
    big = s.createDataFrame({"k": [i % 50 for i in range(2000)],
                             "v": [float(i) for i in range(2000)]})
    small = s.createDataFrame({"k": list(range(50)),
                               "w": [k * 2.0 for k in range(50)]})
    out = (big.join(small, on="k", how="inner")
           .groupBy("k").agg(F.sum(col("v") + col("w")).alias("s"))
           .collect())
    assert len(out) == 50
    joins = find(s.last_plan(), TpuShuffledJoinExec)
    assert joins, s.last_plan()
    j = joins[0]
    assert j.aqe_broadcast_threshold == 1
    # build side is tiny but > 1 byte, so threshold=1 keeps co-partition;
    # re-run with a generous runtime threshold to see the switch
    s2 = TpuSession.builder.config({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "1",
        "spark.rapids.tpu.sql.adaptive.enabled": "true",
        "spark.rapids.tpu.sql.explain": "NONE",
    }).getOrCreate()
    big2 = s2.createDataFrame({"k": [i % 50 for i in range(2000)],
                               "v": [float(i) for i in range(2000)]})
    small2 = s2.createDataFrame({"k": list(range(50)),
                                 "w": [k * 2.0 for k in range(50)]})
    df2 = big2.join(small2, on="k", how="inner") \
        .groupBy("k").agg(F.sum(col("v") + col("w")).alias("s"))
    exec_plan = df2._execute()
    joins = find(exec_plan, TpuShuffledJoinExec)
    assert joins
    joins[0].aqe_broadcast_threshold = 10 << 20   # runtime: plenty
    batch = exec_plan.execute_collect()
    rows = sorted(batch.rows())
    assert len(rows) == 50
    joins[0].metrics.resolve()
    assert joins[0].metrics.get("runtimeBroadcastJoins", 0) == 1, \
        dict(joins[0].metrics)
    # oracle spot check: k=0 -> sum over 40 rows of v + w
    exp0 = sum(float(i) for i in range(0, 2000, 50)) + 40 * 0.0
    assert abs(dict(rows)[0] - exp0) < 1e-6


def test_skew_join_split():
    """AQE skew split: a hot stream partition (one dominant key) larger
    than the skew threshold executes as >=2 mapper-subset tasks joined
    against the SAME shared build partition — results identical to the
    unsplit plan (OptimizeSkewedJoin + partial-mapper partition specs)."""
    import pandas as pd
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.plan.physical import TpuShuffledJoinExec
    from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec

    def find(node, klass):
        out = [node] if isinstance(node, klass) else []
        for c in node.children:
            out.extend(find(c, klass))
        return out

    # 90% of rows share one key -> one hot reduce partition
    ks = [7] * 1800 + [i % 40 for i in range(200)]
    vs = [float(i % 13) for i in range(2000)]
    conf = {
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.tpu.sql.adaptive.enabled": "true",
        "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionThreshold":
            "4096",
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
        "spark.rapids.tpu.sql.explain": "NONE",
    }
    s = TpuSession.builder.config(dict(conf)).getOrCreate()
    big = s.createDataFrame({"k": ks, "v": vs})
    dim = s.createDataFrame({"k": list(range(41)),
                             "w": [k * 10.0 for k in range(41)]})
    rows = sorted(big.join(dim, on="k", how="inner")
                  .select(col("k"), (col("v") + col("w")).alias("x"))
                  .collect())
    joins = find(s.last_plan(), TpuShuffledJoinExec)
    assert joins and joins[0].aqe_skew_threshold == 4096
    m = joins[0].metrics.resolve()
    assert m.get("skewJoinSplits", 0) >= 1, m
    ex_metrics = [e.metrics.resolve()
                  for e in find(s.last_plan(), TpuShuffleExchangeExec)]
    assert any(em.get("skewSplitTasks", 0) >= 2 for em in ex_metrics), \
        ex_metrics
    # oracle: same join without skew splitting
    pb = pd.DataFrame({"k": ks, "v": vs})
    pdim = pd.DataFrame({"k": list(range(41)),
                         "w": [k * 10.0 for k in range(41)]})
    j = pb.merge(pdim, on="k")
    exp = sorted((int(r.k), float(r.v + r.w))
                 for r in j.itertuples(index=False))
    assert rows == exp
