"""Kernel tests: sort encodings, lexsort, filter compaction, concat, segments.

Reference analog: SortExecSuite / GpuCoalesceBatchesSuite-style unit coverage
(SURVEY.md §4 ring 1) against numpy oracles.
"""

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.ops import kernels as K


def _col(vals, dtype):
    return Column.from_pylist(vals, dtype)


def _sorted_pylist(keys, n=None, **kw):
    n = n if n is not None else _count(keys)
    cap = keys[0].column.capacity
    idx = K.sort_indices(keys, n, cap)
    return [k.column.to_pylist(cap) and K.gather_column(k.column, idx).to_pylist(n)
            for k in keys]


def _count(keys):
    return None


def test_sort_ints_asc_nulls_first():
    col = _col([3, None, 1, 2, None], dt.INT64)
    idx = K.sort_indices([K.SortKey(col)], 5, col.capacity)
    out = K.gather_column(col, idx).to_pylist(5)
    assert out == [None, None, 1, 2, 3]


def test_sort_ints_desc_nulls_last():
    col = _col([3, None, 1, 2], dt.INT64)
    idx = K.sort_indices([K.SortKey(col, ascending=False, nulls_first=False)],
                         4, col.capacity)
    out = K.gather_column(col, idx).to_pylist(4)
    assert out == [3, 2, 1, None]


def test_sort_negative_ints():
    col = _col([5, -3, 0, -100, 77], dt.INT64)
    idx = K.sort_indices([K.SortKey(col)], 5, col.capacity)
    assert K.gather_column(col, idx).to_pylist(5) == [-100, -3, 0, 5, 77]


def test_sort_floats_nan_largest():
    col = _col([1.5, float("nan"), -2.0, 0.0], dt.FLOAT64)
    idx = K.sort_indices([K.SortKey(col)], 4, col.capacity)
    out = K.gather_column(col, idx).to_pylist(4)
    assert out[:3] == [-2.0, 0.0, 1.5]
    assert np.isnan(out[3])


def test_sort_floats_desc_nan_first():
    col = _col([1.5, float("nan"), -2.0], dt.FLOAT64)
    idx = K.sort_indices([K.SortKey(col, ascending=False, nulls_first=False)],
                         3, col.capacity)
    out = K.gather_column(col, idx).to_pylist(3)
    assert np.isnan(out[0])
    assert out[1:] == [1.5, -2.0]


def test_sort_strings():
    col = _col(["pear", "apple", None, "banana", "app"], dt.STRING)
    idx = K.sort_indices([K.SortKey(col)], 5, col.capacity)
    out = K.gather_column(col, idx).to_pylist(5)
    assert out == [None, "app", "apple", "banana", "pear"]


def test_sort_multi_key_stability():
    k1 = _col([1, 2, 1, 2, 1], dt.INT32)
    k2 = _col(["b", "x", "a", "y", "a"], dt.STRING)
    idx = K.sort_indices([K.SortKey(k1), K.SortKey(k2)], 5, k1.capacity)
    o1 = K.gather_column(k1, idx).to_pylist(5)
    o2 = K.gather_column(k2, idx).to_pylist(5)
    assert o1 == [1, 1, 1, 2, 2]
    assert o2 == ["a", "a", "b", "x", "y"]


def test_compact_columns():
    col = _col([10, 20, 30, 40, 50], dt.INT64)
    keep = np.zeros(col.capacity, dtype=bool)
    keep[[1, 3]] = True
    import jax.numpy as jnp
    [out], count = K.compact_columns([col], jnp.asarray(keep))
    assert int(count) == 2
    assert out.to_pylist(2) == [20, 40]
    # rows beyond count are invalid
    assert not bool(np.asarray(out.validity)[2:].any())


def test_concat_columns():
    a = _col([1, 2], dt.INT64)
    b = _col([3, None], dt.INT64)
    out = K.concat_columns([a, b], [2, 2], 256)
    assert out.to_pylist(4) == [1, 2, 3, None]
    assert out.capacity == 256


def test_concat_string_width_mismatch():
    a = _col(["ab"], dt.STRING)
    b = _col(["longer-string-here"], dt.STRING)
    out = K.concat_columns([a, b], [1, 1], 128)
    assert out.to_pylist(2) == ["ab", "longer-string-here"]


def test_segment_starts_and_ids():
    col = _col([1, 1, 2, 2, 2, None, None], dt.INT64)
    starts = K.segment_starts_from_sorted_keys([col], 7, col.capacity)
    s = np.asarray(starts)[:7]
    assert list(s) == [True, False, True, False, False, True, False]
    ids = np.asarray(K.segment_ids(starts))[:7]
    assert list(ids) == [0, 0, 1, 1, 1, 2, 2]


def test_slice_column():
    col = _col([0, 1, 2, 3, 4, 5], dt.INT64)
    out = K.slice_column(col, 2, 128, 3)
    assert out.to_pylist(3) == [2, 3, 4]
