"""MAP type + map expressions (ref: complexTypeExtractors.scala GetMapValue,
complexTypeCreator.scala CreateMap, collectionOperations.scala
MapKeys/MapValues). Device layout: int64[cap, 3W] bitpattern matrix, see
ops/maps.py."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch

from golden import assert_tpu_and_cpu_equal


def _map_table():
    return pa.table({
        "k": [1, 2, 3, 4],
        "m": pa.array([[(1, 5.0), (2, 6.5)], [(2, 7.0)], None, []],
                      type=pa.map_(pa.int64(), pa.float64())),
    })


def test_map_roundtrip_arrow():
    b = ColumnarBatch.from_arrow(_map_table())
    assert dt.is_map(b.schema["m"].dtype)
    assert b.to_pydict()["m"] == [{1: 5.0, 2: 6.5}, {2: 7.0}, None, {}]
    rt = ColumnarBatch.from_arrow(b.to_arrow())
    assert rt.to_pydict() == b.to_pydict()


def test_map_null_values_roundtrip():
    sch = dt.Schema([dt.Field("m", dt.MAP(dt.INT64, dt.FLOAT64))])
    b = ColumnarBatch.from_pydict({"m": [{1: 2.5, 3: None}, None]},
                                  schema=sch)
    assert b.to_pydict()["m"] == [{1: 2.5, 3: None}, None]


def test_get_map_value_golden():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_map_table())
        .select(col("k"), F.get_item(col("m"), 2).alias("two"),
                F.element_at(col("m"), 1).alias("one")))


def test_map_keys_values_size_golden():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_map_table())
        .select(col("k"), F.map_keys(col("m")).alias("ks"),
                F.map_values(col("m")).alias("vs"),
                F.size(col("m")).alias("n")))


def test_create_map_golden():
    """Int keys/values build on device; float values fall back to the CPU
    engine (the backend cannot bit-pack f64 on device) but stay correct."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(
            {"a": [1, 2, 3, 2], "b": [10, 20, None, 40]})
        .select(F.create_map(col("a"), col("b")).alias("m")))
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(
            {"a": [1, 2, 3, 2], "x": [1.5, 2.5, None, 4.5],
             "b": [10, 20, 30, 40]})
        .select(F.create_map(col("a"), col("x"), col("b"),
                             F.col("x") + lit(1.0)).alias("m")),
        expect_fallback=["Project"])


def test_create_map_last_win_dedup():
    """Duplicate keys keep the LAST entry (mapKeyDedupPolicy=LAST_WIN)."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"a": [7, 7], "x": [1, 2],
                                     "y": [3, 4]})
        .select(F.create_map(col("a"), col("x"), col("a"),
                             col("y")).alias("m")))


def test_map_then_filter_groupby():
    """Map lookup feeding the filter->groupby pipeline end to end."""
    rng = np.random.default_rng(11)
    n = 5000
    keys = rng.integers(0, 8, n)
    maps = [{int(k): float(k) * 2 + 1, 99: -1.0} for k in keys]
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(
            {"g": [int(x) for x in keys % 4], "m": maps})
        .select(col("g"), F.get_item(col("m"), 99).alias("v"))
        .groupBy("g").agg(F.sum("v").alias("sv"),
                          F.count("*").alias("c")),
        ignore_order=True)


def test_float_key_map():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"m": [{1.5: 10}, {2.5: 20}, None]})
        .select(F.get_item(col("m"), 1.5).alias("x")))


def test_create_map_dedup_keeps_first_position_last_value():
    """Spark's ArrayBasedMapBuilder: a duplicate key keeps its FIRST
    position in entry order but its LAST value — map_keys order proves it
    (dict-compare alone cannot)."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"a": [2, 5], "x": [1, 2],
                                     "y": [3, 4]})
        .select(F.map_keys(F.create_map(
            lit(1), col("x"), col("a"), col("y"),
            lit(1), col("x") + lit(10))).alias("ks"),
            F.map_values(F.create_map(
                lit(1), col("x"), col("a"), col("y"),
                lit(1), col("x") + lit(10))).alias("vs")))


def test_get_item_numpy_key():
    import numpy as _np
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"m": [{1: 2.0}, {3: 4.0}]})
        .select(F.get_item(col("m"), _np.int64(1)).alias("x"),
                F.element_at(col("m"), _np.int64(3)).alias("y")))


def test_map_width_harmonization_concat():
    """Interleaved lanes survive the var-width padding every concat path
    applies (a side-by-side block layout would shift and corrupt)."""
    from spark_rapids_tpu.plan.physical import concat_batches
    sch = dt.Schema([dt.Field("m", dt.MAP(dt.INT64, dt.INT64))])
    narrow = ColumnarBatch.from_pydict({"m": [{1: 10}]}, schema=sch)
    wide = ColumnarBatch.from_pydict(
        {"m": [{i: i * 2 for i in range(7)}]}, schema=sch)
    assert narrow.columns[0].data.shape[1] < wide.columns[0].data.shape[1]
    out = concat_batches(sch, [narrow, wide])
    assert out.to_pydict()["m"] == [{1: 10},
                                    {i: i * 2 for i in range(7)}]


def test_empty_map_only_column():
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.getOrCreate()
    out = s.createDataFrame({"m": [{}, None]}).select(
        F.size(col("m")).alias("n")).collect()
    assert out == [(0,), (-1,)]


def test_float_lookup_key_on_int_map():
    """A 1.5 lookup on an int-keyed map must NOT truncate-match entry 1."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"m": [{1: 2}, {2: 3}]})
        .select(F.get_item(col("m"), 1.5).alias("x")))


def test_element_at_negative_index_array():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(
            {"a": [[1, 2, 3], [7], None, []]})
        .select(F.element_at(col("a"), -1).alias("last"),
                F.element_at(col("a"), 1).alias("first")))


def test_string_key_map_falls_back():
    """String-keyed maps have no device layout: CPU fallback, correct."""
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.getOrCreate()
    df = s.createDataFrame({"m": [{"a": 1}, {"b": 2}, None]})
    out = df.select(F.get_item(col("m"), lit("a")).alias("x")).collect()
    assert out == [(1,), (None,), (None,)]


def test_string_key_map_collect_roundtrip():
    """map<string,_> columns (CPU-engine-only dtype) must survive the
    host collect boundary as python objects instead of crashing in the
    device bitpattern encoding (ObjectColumn path)."""
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.getOrCreate()
    out = s.createDataFrame({"m": [{"a": 1}, {"b": 2}, None]}).collect()
    assert out == [({"a": 1},), ({"b": 2},), (None,)]
    # mixed with a device column, and arrow round-trip
    df = s.createDataFrame({"m": [{"a": 1}, {"b": 2}], "k": [1, 2]})
    assert df.collect() == [({"a": 1}, 1), ({"b": 2}, 2)]
    at = df.to_arrow()
    assert at.column("m").to_pylist() == [[("a", 1)], [("b", 2)]]


def test_map_infer_widens_across_rows():
    """Value-type inference scans every dict: int-then-float columns must
    widen to double instead of silently truncating later rows."""
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.getOrCreate()
    out = s.createDataFrame({"m": [{1: 1}, {2: 2.5}]}).collect()
    assert out == [({1: 1.0},), ({2: 2.5},)]


def test_bigint_lookup_on_narrow_key_map_no_wrap():
    """A bigint lookup key larger than 2^32 must not wrap modulo 2^32 and
    falsely match a narrow map key (integral compares happen in int64)."""
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.getOrCreate()
    big = (1 << 32) + 1
    df = s.createDataFrame({"m": [{1: 10}, {big: 20}]})
    out = df.select(F.get_item(col("m"), lit(big)).alias("x")).collect()
    assert out == [(None,), (20,)]
