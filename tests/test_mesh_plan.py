"""Mesh-routed planner execution: with ``mesh.enabled=true`` the planner
emits fused SPMD execs (group-by / join / sort over all_to_all collectives)
and results still match the CPU oracle. Runs on the virtual 8-device CPU
mesh from conftest.
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col

from golden import assert_tpu_and_cpu_equal

MESH_ON = {"spark.rapids.tpu.sql.mesh.enabled": "true",
           "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1"}


def _find(node, klass):
    out = [node] if isinstance(node, klass) else []
    for c in node.children:
        out.extend(_find(c, klass))
    return out


def _seeded(n=1500, nkeys=23):
    rng = np.random.default_rng(13)
    return pd.DataFrame({
        "k": rng.integers(0, nkeys, n),
        "v": np.where(rng.random(n) < 0.9, rng.normal(0, 10, n), np.nan),
        "j": rng.integers(-4, 4, n),
    })


def test_mesh_groupby_planned_and_correct():
    from spark_rapids_tpu.parallel.mesh_exec import TpuMeshGroupByExec
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(_seeded())
                .groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("v").alias("c"),
                                  F.avg("v").alias("a"),
                                  F.min("j").alias("mn"),
                                  F.max("j").alias("mx")))

    assert_tpu_and_cpu_equal(q, approx=1e-9, conf=MESH_ON)
    plan = captured["s"].last_plan()
    assert _find(plan, TpuMeshGroupByExec), plan


def test_mesh_groupby_null_keys_and_count_star():
    df = pd.DataFrame({"k": [1.0, None, 2.0, None, 1.0] * 30,
                       "v": np.arange(150, dtype=np.float64)})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(df)
        .groupBy("k").agg(F.count("*").alias("n"), F.sum("v").alias("sv")),
        approx=1e-9, conf=MESH_ON)


def test_mesh_groupby_skewed_single_key():
    n = 4000
    df = pd.DataFrame({"k": np.ones(n, dtype=np.int64),
                       "v": np.arange(n, dtype=np.float64)})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(df)
        .groupBy("k").agg(F.sum("v").alias("s"), F.count("v").alias("c")),
        approx=1e-9, conf=MESH_ON)


def test_mesh_complex_agg_falls_back_to_host_plan():
    """sum(v)+sum(j) is not a bare leaf: the mesh route declines and the
    host path still answers correctly."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .groupBy("k").agg((F.sum("v") + F.sum("j")).alias("t")),
        approx=1e-9, conf=MESH_ON)


@pytest.mark.parametrize("how", ["inner", "left", "full", "left_semi",
                                 "left_anti"])
def test_mesh_join_planned_and_correct(how):
    from spark_rapids_tpu.parallel.mesh_exec import TpuMeshJoinExec
    rng = np.random.default_rng(17)
    left = pd.DataFrame({"a": rng.integers(0, 40, 300),
                         "x": rng.normal(0, 1, 300)})
    right = pd.DataFrame({"b": rng.integers(20, 60, 200),
                          "y": rng.integers(0, 9, 200)})
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(left)
                .join(s.createDataFrame(right), on=(col("a") == col("b")),
                      how=how))

    assert_tpu_and_cpu_equal(q, approx=1e-9, conf=MESH_ON)
    assert _find(captured["s"].last_plan(), TpuMeshJoinExec)


def test_mesh_sort_total_order():
    from spark_rapids_tpu.parallel.mesh_exec import TpuMeshSortExec
    rng = np.random.default_rng(19)
    df = pd.DataFrame({"k": rng.permutation(2000),
                       "v": rng.normal(0, 1, 2000)})
    captured = {}

    def q(s):
        captured["s"] = s
        return s.createDataFrame(df).orderBy("k")

    assert_tpu_and_cpu_equal(q, approx=1e-12, ignore_order=False,
                             conf=MESH_ON)
    assert _find(captured["s"].last_plan(), TpuMeshSortExec)


def test_mesh_sort_desc_with_nulls():
    rng = np.random.default_rng(23)
    vals = rng.normal(0, 50, 600)
    k = np.where(rng.random(600) < 0.15, np.nan, vals)
    df = pd.DataFrame({"k": k, "i": np.arange(600)})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(df)
        .orderBy(F.col("k").desc(), F.col("i")),
        approx=1e-12, ignore_order=False, conf=MESH_ON)


def test_mesh_sort_skew():
    """Heavily duplicated keys: bounds collapse, rows pile onto few workers,
    the n*cap receive window absorbs it."""
    rng = np.random.default_rng(29)
    k = np.where(rng.random(1600) < 0.85, 42, rng.integers(0, 500, 1600))
    df = pd.DataFrame({"k": k, "u": np.arange(1600)})
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(df).orderBy("k", "u"),
        ignore_order=False, conf=MESH_ON)


def test_mesh_pipeline_groupby_then_sort():
    """Compose SPMD stages: mesh group-by feeding a mesh sort."""
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_seeded())
        .groupBy("k").agg(F.sum("v").alias("sv"))
        .orderBy("k"),
        approx=1e-9, ignore_order=False, conf=MESH_ON)


def test_mesh_groupby_streams_past_max_stage_bytes():
    """An input ABOVE mesh.maxStageBytes stays on the mesh (streaming
    multi-round path) instead of falling back to the host exchange
    (round-3 VERDICT weak#6 / item 7)."""
    import numpy as np
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.parallel.mesh_exec import TpuMeshGroupByExec

    s = TpuSession.builder.config({
        "spark.rapids.tpu.sql.mesh.enabled": "true",
        "spark.rapids.tpu.sql.mesh.maxStageBytes": "4096",   # tiny bound
        "spark.rapids.tpu.sql.mesh.streamWindowRows": "1024",
        "spark.rapids.tpu.sql.explain": "NONE",
    }).getOrCreate()
    rng = np.random.default_rng(5)
    n = 20_000                              # ~320 KB >> 4 KB bound
    ks = np.where(rng.random(n) < 0.5, 0, rng.integers(0, 40, n))
    df = s.createDataFrame({"k": [int(x) for x in ks],
                            "v": [float(x) for x in rng.normal(0, 3, n)]})
    got = sorted(df.groupBy("k").agg(
        F.sum("v").alias("s"), F.count("v").alias("c"),
        F.avg("v").alias("a")).collect())

    def find(node, klass):
        out = [node] if isinstance(node, klass) else []
        for c in node.children:
            out.extend(find(c, klass))
        return out
    execs = find(s.last_plan(), TpuMeshGroupByExec)
    assert execs and execs[0].window_rows == 1024, s.last_plan()

    exp = {}
    d = df.toPandas()
    for k, g in d.groupby("k"):
        exp[int(k)] = (float(g.v.sum()), int(g.v.count()),
                       float(g.v.mean()))
    assert len(got) == len(exp)
    for k, sv, cv, av in got:
        es, ec, ea = exp[int(k)]
        assert abs(sv - es) < 1e-6 and cv == ec and abs(av - ea) < 1e-9


def test_streaming_mesh_groupby_string_keys():
    """VERDICT r4 item 7: a STRING-key group-by larger than maxStageBytes
    stays MESH-routed through the streaming path (exact int64
    word-encoding of the keys; no silent host-exchange fallback)."""
    import numpy as np
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.parallel.mesh_exec import TpuMeshGroupByExec

    s = TpuSession.builder.config({
        "spark.rapids.tpu.sql.mesh.enabled": "true",
        "spark.rapids.tpu.sql.mesh.maxStageBytes": "4096",
        "spark.rapids.tpu.sql.mesh.streamWindowRows": "1024",
        "spark.rapids.tpu.sql.explain": "NONE",
    }).getOrCreate()
    rng = np.random.default_rng(11)
    n = 20_000
    cats = ["alpha", "beta", "gamma", "delta", "epsilon-longer-name",
            "zeta", "", "eta#with#marks"]
    ks = [cats[i] for i in rng.integers(0, len(cats), n)]
    df = s.createDataFrame({"k": ks,
                            "v": [float(x) for x in rng.normal(1, 2, n)]})
    got = sorted(df.groupBy("k").agg(
        F.sum("v").alias("s"), F.count("v").alias("c"),
        F.avg("v").alias("a")).collect())

    def find(node, klass):
        out = [node] if isinstance(node, klass) else []
        for c in node.children:
            out.extend(find(c, klass))
        return out
    execs = find(s.last_plan(), TpuMeshGroupByExec)
    assert execs and execs[0].window_rows == 1024, s.last_plan()

    d = df.toPandas()
    exp = {}
    for k, g in d.groupby("k"):
        exp[k] = (float(g.v.sum()), int(g.v.count()), float(g.v.mean()))
    assert len(got) == len(exp), (len(got), len(exp))
    for k, sv, cv, av in got:
        es, ec, ea = exp[k]
        assert abs(sv - es) < 1e-6 and cv == ec and abs(av - ea) < 1e-9, k
