"""Multi-process shuffle manager: one planner-driven query runs across
two OS processes over the TCP transport (VERDICT round-3 item 3 — the
local/remote split of RapidsCachingReader.scala:49-148 +
RapidsShuffleInternalManager.scala:200-374).

Each worker process bootstraps a WorkerContext (its own ShuffleStore +
ShuffleServer), registers its LOCAL data shard, and runs the same logical
query; the planner inserts partial->exchange->final aggregates and
co-partitioned shuffled joins whose exchanges route map slices into the
local store and fetch peers' slices over TCP. Every worker's collect
yields the rows of its owned reduce partitions; the parent combines and
golden-compares against pandas."""

import os
import subprocess
import sys
import json

import pandas as pd
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import sys, json, socket, time
sys.path.insert(0, {repo!r})
import os
os.environ.setdefault("SPARK_RAPIDS_TPU_COMPILE_CACHE", "off")
from spark_rapids_tpu.shuffle.manager import init_worker

wid = int(sys.argv[1]); n = int(sys.argv[2]); query = sys.argv[3]
ctx = init_worker(wid, n)
print(json.dumps({{"port": ctx.port}}), flush=True)
peers = json.loads(sys.stdin.readline())
ctx.set_peers({{int(k): tuple(v) for k, v in peers.items()}})

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col

conf = {{"spark.rapids.tpu.sql.explain": "NONE",
         "spark.rapids.tpu.sql.shuffle.partitions": "4"}}
if query == "join_agg":
    # keep the co-partitioned path exercised: without this, the tiny dim
    # table flips the runtime AQE switch and the shuffled join never runs
    conf["spark.rapids.tpu.sql.autoBroadcastJoinThreshold"] = "-1"
s = TpuSession.builder.config(conf).getOrCreate()

# each worker holds its own data SHARD (disjoint by construction)
base = wid * 1000
ks = [(base + i) % 7 for i in range(200)]
vs = [float(i % 13) for i in range(200)]
s.createDataFrame({{"k": ks, "v": vs}}).createOrReplaceTempView("t")
rk = list(range(7))
s.createDataFrame({{"k": rk, "w": [k * 10.0 for k in rk]}}) \\
    .createOrReplaceTempView("dim" )

if query == "agg":
    out = s.sql("SELECT k, sum(v) AS sv, count(*) AS c FROM t GROUP BY k") \\
        .collect()
elif query in ("join_agg", "join_agg_aqe"):
    out = (s.table("t")
           .join(s.table("dim"), on="k", how="inner")
           .groupBy("k")
           .agg(F.sum(col("v") + col("w")).alias("sv"))
           .collect())
else:
    raise SystemExit(f"unknown query {{query}}")

rtb = 0
def _walk(n):
    global rtb
    rtb += int(n.metrics.resolve().get("runtimeBroadcastJoins", 0))
    for c in n.children:
        _walk(c)
_walk(s.last_plan())
print(json.dumps({{"rows": [list(r) for r in out], "rtb": rtb}}), flush=True)
ctx.shutdown()
"""


def _run_cluster(query: str, n_workers: int = 2):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    procs = []
    for wid in range(n_workers):
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER.format(repo=_REPO),
             str(wid), str(n_workers), query],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=env, text=True))
    try:
        ports = {}
        for wid, p in enumerate(procs):
            line = p.stdout.readline()
            assert line, p.stderr.read()
            ports[wid] = ("127.0.0.1", json.loads(line)["port"])
        peers = json.dumps({str(w): list(a) for w, a in ports.items()})
        for p in procs:
            p.stdin.write(peers + "\n")
            p.stdin.flush()
        rows, rtb = [], 0
        for p in procs:
            out, err = p.communicate(timeout=300)
            for line in out.splitlines():
                try:
                    d = json.loads(line)
                    rows.extend(tuple(r) for r in d["rows"])
                    rtb += d.get("rtb", 0)
                except (json.JSONDecodeError, KeyError):
                    continue
            assert p.returncode == 0, err
        return rows, rtb
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def _shards(n_workers: int = 2):
    frames = []
    for wid in range(n_workers):
        base = wid * 1000
        frames.append(pd.DataFrame({
            "k": [(base + i) % 7 for i in range(200)],
            "v": [float(i % 13) for i in range(200)]}))
    return pd.concat(frames)


def test_two_process_planner_driven_aggregate():
    """Two-phase agg: partial -> hash exchange (over TCP between two OS
    processes) -> final; union of both workers' owned partitions equals
    the pandas oracle over the union of shards."""
    rows, _ = _run_cluster("agg")
    got = sorted(rows)
    oracle = _shards().groupby("k").agg(sv=("v", "sum"), c=("v", "count"))
    exp = sorted((int(k), float(r["sv"]), int(r["c"]))
                 for k, r in oracle.iterrows())
    assert got == exp


def _join_agg_oracle():
    sh = _shards()
    dim = pd.DataFrame({"k": list(range(7)),
                        "w": [k * 10.0 for k in range(7)]})
    j = sh.merge(dim, on="k")
    oracle = (j.assign(x=j.v + j.w).groupby("k").x.sum())
    # the dim table is REPLICATED on both workers (a registered dimension,
    # not a shard): the join therefore sees it twice across the cluster —
    # matching real deployments where dims are broadcast-registered
    # per-worker; the oracle doubles it accordingly
    return sorted((int(k), 2 * float(v)) for k, v in oracle.items())


def test_two_process_shuffled_join_plus_aggregate():
    """Co-partitioned shuffled join (both sides exchanged across the two
    processes; static broadcast is disabled because each worker only holds
    a shard of the build side, and the runtime switch is off via
    threshold=-1) followed by a grouped aggregate."""
    rows, rtb = _run_cluster("join_agg")
    assert rtb == 0                       # stayed co-partitioned
    assert sorted(rows) == _join_agg_oracle()


def test_two_process_mesh_consistent_runtime_broadcast():
    """AQE runtime join switch ACROSS WORKERS: the build-side exchange's
    observed size is summed through the control-plane allreduce, every
    worker takes the same branch, and a switch materializes the COMPLETE
    build side (all peers' slices) before broadcast-joining the raw local
    stream shard — same rows as the co-partitioned plan."""
    rows, rtb = _run_cluster("join_agg_aqe")
    assert rtb == 2                       # both workers switched
    assert sorted(rows) == _join_agg_oracle()


def test_fetch_when_complete_waits_for_late_map():
    """A reduce-side fetch issued BEFORE the peer finished (or even
    started) its map phase polls until the completion mark instead of
    reading partial data (the stage-ordering guarantee)."""
    import threading
    import time
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.shuffle.transport import (ShuffleClient,
                                                    ShuffleServer,
                                                    ShuffleStore)
    store = ShuffleStore()
    srv = ShuffleServer(store, port=0).start()
    try:
        def late_map():
            time.sleep(0.3)
            b = ColumnarBatch.from_pydict({"a": [1, 2, 3]})
            store.register_batch(7, 0, b.fetch_to_host())
            store.mark_complete(7)
        t = threading.Thread(target=late_map)
        t.start()
        client = ShuffleClient.for_address("127.0.0.1", srv.port)
        got = client.fetch_when_complete(7, [0], timeout_s=10)
        t.join()
        assert len(got) == 1 and sorted(got[0].rows()) == [(1,), (2,), (3,)]
    finally:
        srv.stop()


def test_fetch_when_complete_times_out():
    """A peer that never completes surfaces ShuffleFetchError (the
    RapidsShuffleFetchFailedException analog the caller maps to a stage
    retry)."""
    from spark_rapids_tpu.shuffle.transport import (ShuffleClient,
                                                    ShuffleFetchError,
                                                    ShuffleServer,
                                                    ShuffleStore)
    srv = ShuffleServer(ShuffleStore(), port=0).start()
    try:
        client = ShuffleClient.for_address("127.0.0.1", srv.port)
        with pytest.raises(ShuffleFetchError):
            client.fetch_when_complete(9, [0], timeout_s=0.4, poll_s=0.05)
    finally:
        srv.stop()
