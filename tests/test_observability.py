"""Per-operator metrics, EXPLAIN ANALYZE, query listeners, and the
Chrome-trace timeline (ISSUE 6): the observability layer the reference
surfaces through SQLMetrics in the Spark UI (GpuExec.scala:27-56) plus
NVTX ranges (NvtxWithMetrics.scala:27), reproduced as exec-attributed
metric bags + a text EXPLAIN ANALYZE + trace.json export."""

import json
import threading

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec import metrics as em
from spark_rapids_tpu.exec.tracing import (SpanRecorder, SyncCounter,
                                           trace_span)


def _session(**conf):
    return TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE", **conf}).getOrCreate()


def _q3_tables(s, n=8192):
    rng = np.random.default_rng(7)
    line = pd.DataFrame({
        "l_order": rng.integers(0, 1000, n).astype("int64"),
        "l_price": rng.normal(100.0, 10.0, n)})
    orders = pd.DataFrame({
        "o_key": np.arange(1000, dtype="int64"),
        "o_cust": rng.integers(0, 100, 1000).astype("int64"),
        "o_date": rng.integers(0, 1000, 1000).astype("int64")})
    cust = pd.DataFrame({
        "c_key": np.arange(100, dtype="int64"),
        "c_seg": rng.integers(0, 3, 100).astype("int64")})
    s.createDataFrame(line).createOrReplaceTempView("o_lineitem")
    s.createDataFrame(orders).createOrReplaceTempView("o_orders")
    s.createDataFrame(cust).createOrReplaceTempView("o_customer")
    exp = (line.merge(orders, left_on="l_order", right_on="o_key")
               .merge(cust, left_on="o_cust", right_on="c_key"))
    return exp[(exp.o_date < 700) & (exp.c_seg == 1)]


Q3_SQL = ("SELECT l_price, o_date, c_seg FROM o_lineitem "
          "JOIN o_orders ON l_order = o_key "
          "JOIN o_customer ON o_cust = c_key "
          "WHERE o_date < 700 AND c_seg = 1")


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE on the q3-shaped 3-way join
# ---------------------------------------------------------------------------

def test_q3_explain_analyze_rows_consistent_and_join_syncs_o1():
    s = _session(**{"spark.rapids.tpu.sql.reader.batchSizeRows": 1024})
    exp = _q3_tables(s)
    rows = s.sql(Q3_SQL).collect()
    assert len(rows) == len(exp)

    # the metrics tree's ROOT numOutputRows must equal the collected rows
    ops = s.last_query_metrics()["operators"]
    root = ops[0]
    assert root["metrics"].get("numOutputRows") == len(rows), root

    # every join node's attributed hostSyncs stays O(1) per stage: the
    # pipelined window batches its sizing readbacks (one per half-window),
    # so per-batch syncs would show ~8+ here
    joins = [o for o in ops if "JoinExec" in o["operator"]]
    assert joins, ops
    for j in joins:
        assert j["metrics"].get("hostSyncs", 0) <= 4, j

    # the rendered EXPLAIN ANALYZE names the join nodes with their
    # per-node metrics inline and carries the query-level summary
    text = s.explain_analyze()
    assert "== Executed Plan (analyzed) ==" in text
    assert "TpuSortMergeJoinExec" in text
    assert f"numOutputRows: {len(rows)}" in text
    assert "hostSyncs" in text and "executeTimeS=" in text


def test_df_explain_analyze_executes_and_prints(capsys):
    s = _session()
    df = s.createDataFrame(pd.DataFrame(
        {"k": [1, 2, 1, 3] * 16, "v": [1., 2., 3., 4.] * 16}))
    agg = df.groupBy("k").agg(F.sum("v").alias("sv"))
    agg.explain("analyze")          # executes the frame (Spark semantics)
    text = capsys.readouterr().out
    assert "== Executed Plan (analyzed) ==" in text
    assert "TpuHashAggregateExec" in text
    assert "numOutputRows: 3" in text


def test_contract_violation_attaches_to_analyzed_tree(capsys):
    """A seeded schema corruption must show on ITS node in EXPLAIN
    ANALYZE, not only in the flat warn log."""
    from spark_rapids_tpu.columnar import dtypes as dt
    s = _session(**{"spark.rapids.tpu.sql.analysis.validatePlan": "warn"})
    df = s.createDataFrame(pd.DataFrame({"a": [1.0, 2.0, 3.0]}))
    df = df.filter(F.col("a") > 0)
    df._execute()
    plan = s.last_plan()
    # corrupt the filter's passthrough schema after conversion, then
    # re-validate the way Overrides does and render
    from spark_rapids_tpu.analysis import contracts
    from spark_rapids_tpu.plan.physical import TpuFilterExec

    def find(node):
        if isinstance(node, TpuFilterExec):
            return node
        for c in node.children:
            got = find(c)
            if got is not None:
                return got
        return None

    filt = find(plan)
    assert filt is not None, plan
    filt._schema = dt.Schema([dt.Field(f.name, dt.INT64, f.nullable)
                              for f in filt._schema])
    violations = contracts.validate_plan(plan, None)
    assert violations
    s._last_overrides.last_violations = violations
    text = s.explain_analyze()
    assert "! contract:" in text


# ---------------------------------------------------------------------------
# Query-execution listener API
# ---------------------------------------------------------------------------

def test_listener_receives_executed_plan_and_reports():
    s = _session()
    captured = []
    s.register_query_listener(captured.append)
    try:
        df = s.createDataFrame(pd.DataFrame(
            {"k": [1, 2, 1] * 8, "v": [1., 2., 3.] * 8}))
        df.groupBy("k").agg(F.sum("v").alias("sv")).collect()
    finally:
        s.unregister_query_listener(captured.append)
    assert len(captured) == 1
    qe = captured[0]
    assert qe.plan is s.last_plan()
    assert qe.metrics_tree and qe.metrics_tree[0][0] == 0
    assert "hostSyncs" in qe.sync
    assert "wallS" in qe.spans
    assert isinstance(qe.recompiles, dict) and isinstance(qe.locks, dict)
    assert "TpuHashAggregateExec" in qe.explain_analyze()
    # unregistered: no further captures
    s.createDataFrame(pd.DataFrame({"x": [1]})).collect()
    assert len(captured) == 1


def test_listener_errors_never_fail_the_query():
    s = _session()

    def bad(_qe):
        raise RuntimeError("listener bug")

    s.register_query_listener(bad)
    try:
        out = s.createDataFrame(pd.DataFrame({"x": [1, 2]})).collect()
        assert [r[0] for r in out] == [1, 2]
    finally:
        s.unregister_query_listener(bad)


# ---------------------------------------------------------------------------
# Chrome-trace timeline exporter
# ---------------------------------------------------------------------------

def test_timeline_round_trips_valid_chrome_trace(tmp_path):
    s = _session(**{"spark.rapids.tpu.sql.tracing.timeline": "true"})
    try:
        df = s.createDataFrame(pd.DataFrame(
            {"k": [1, 2, 1, 3] * 64, "v": [1., 2., 3., 4.] * 64}))
        df.groupBy("k").agg(F.sum("v").alias("sv")).collect()
        rec = s._last_span_recorder
        path = rec.dump_chrome_trace(str(tmp_path / "trace.json"))
        tr = json.load(open(path))           # round-trips as valid JSON
        evs = tr["traceEvents"]
        xs = [e for e in evs if e["ph"] == "X"]
        assert xs, "timeline recorded no spans"
        named_tids = {e["tid"] for e in evs
                      if e["ph"] == "M" and e["name"] == "thread_name"}
        for e in xs:
            # event pairing: every complete event carries begin + duration
            assert e["ts"] >= 0.0 and e["dur"] >= 0.0, e
            assert e["name"] and e["tid"] in named_tids, e
        # the span names match the flat report's names
        rep_names = {n for n in rec.report()
                     if n not in ("wallS", "concurrency")}
        assert {e["name"] for e in xs} <= rep_names | {"process_name"}
    finally:
        from spark_rapids_tpu.exec import tracing
        tracing.reset_cache()


def test_timeline_off_by_default_records_no_events():
    s = _session()
    s.createDataFrame(pd.DataFrame({"x": [1, 2, 3]})).collect()
    rec = s._last_span_recorder
    assert rec.chrome_trace()["traceEvents"] == [
        {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
         "args": {"name": "spark-rapids-tpu query"}}]


def test_timeline_names_task_pool_threads(tmp_path):
    """Multi-partition drains run on the named task pool; the timeline's
    thread metadata must carry those names (PR 4 named them)."""
    rec = SpanRecorder(timeline=True)
    from spark_rapids_tpu.exec.tasks import run_partition_tasks
    with rec:
        def body(pid, part):
            with trace_span(f"part_{pid}"):
                return pid
        run_partition_tasks([1, 2, 3, 4], body, max_workers=4)
    names = {e["args"]["name"]
             for e in rec.chrome_trace()["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert any(n.startswith("tpu-task") for n in names), names


# ---------------------------------------------------------------------------
# SpanRecorder wallS + concurrency
# ---------------------------------------------------------------------------

def test_span_report_wall_and_concurrency():
    import time
    rec = SpanRecorder()
    with rec:
        with trace_span("outer"):
            time.sleep(0.02)
    rep = rec.report()
    assert rep["wallS"] >= 0.02
    assert rep["outer"]["selfS"] >= 0.02
    # single-threaded, no suspension: self-time ~ wall
    assert 0.5 <= rep["concurrency"] <= 1.5, rep


def test_span_report_concurrency_past_one_with_threads():
    import time
    rec = SpanRecorder()

    def worker():
        with trace_span("w"):
            time.sleep(0.05)

    with rec:
        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    rep = rec.report()
    # 4 threads x 0.05s inside a ~0.05s wall: the ratio names the
    # parallelism instead of looking like double counting
    assert rep["concurrency"] > 1.5, rep


# ---------------------------------------------------------------------------
# Exec attribution (innermost open exec)
# ---------------------------------------------------------------------------

def test_attribute_routes_to_innermost_open_exec():
    inner = em.TpuMetrics()
    outer = em.TpuMetrics()
    with trace_span("o", outer):
        em.attribute("hostSyncs")
        with trace_span("i", inner):
            em.attribute("hostSyncs")
            em.attribute("spillBytes", 128)
    assert dict(inner) == {"hostSyncs": 1, "spillBytes": 128}
    assert dict(outer) == {"hostSyncs": 1}
    assert em.current() is None            # scopes unwound


def test_attribute_outside_any_exec_is_noop():
    em.attribute("hostSyncs")              # must not raise
    assert em.current() is None


def test_metrics_disabled_conf_stops_collection():
    s = _session(**{"spark.rapids.tpu.sql.metrics.enabled": "false"})
    try:
        s.createDataFrame(pd.DataFrame({"x": [1, 2, 3]})).collect()
        ops = s.last_query_metrics()["operators"]
        assert all(not o["metrics"] for o in ops), ops
    finally:
        em.reset_cache()
        _session()                          # restore default-conf session


# ---------------------------------------------------------------------------
# SyncCounter default stack under concurrent enter/exit
# ---------------------------------------------------------------------------

def test_sync_counter_stack_survives_concurrent_enter_exit():
    errs = []

    def hammer():
        try:
            for _ in range(200):
                with SyncCounter():
                    pass
        except Exception as e:              # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=hammer) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    assert SyncCounter._default_stack == []


# ---------------------------------------------------------------------------
# Bench preflight (the un-darkened bench)
# ---------------------------------------------------------------------------

def test_preflight_timeout_degrades_to_labeled_cpu():
    from benchmarks.preflight import probe_devices
    probe = probe_devices(timeout_s=0.01)   # nothing spawns in 10ms
    assert probe["ok"] is False
    assert "timed out" in probe["error"]
    assert probe["latencyS"] >= 0.0
    # the preflight labeling contract: a failed probe means an explicit
    # cpu-degraded backend, never a zeroed value
    backend = probe["platform"] if probe["ok"] else "cpu-degraded"
    assert backend == "cpu-degraded"


@pytest.mark.slow
def test_preflight_probe_succeeds_on_cpu():
    from benchmarks.preflight import preflight
    pf = preflight(timeout_s=60)
    assert pf["deviceProbe"]["ok"] is True
    assert pf["backend"] == "cpu"
    assert pf["deviceProbe"]["latencyS"] > 0
