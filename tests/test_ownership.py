"""Buffer-lifecycle ledger tests (ISSUE 19): the runtime half of the
device-memory ownership discipline — record/enforce modes, tombstoned
frees raising typed use-after-free, donation tombstones, the
end-of-query residency audit, and the q3-shaped acceptance run under
``bufferLedger=enforce`` + ``lockdep=enforce`` with watermarks back at
zero. The static half lives in tests/test_static_analysis.py.
"""

import numpy as np
import pytest

from spark_rapids_tpu.analysis import ledger
from spark_rapids_tpu.analysis.ledger import (BufferLeakError,
                                              DoubleFreeError,
                                              UseAfterDonateError,
                                              UseAfterFreeError)
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec import query_context as qc
from spark_rapids_tpu.exec.spill import (CACHE_PRIORITY, BufferCatalog,
                                         SpillableColumnarBatch,
                                         StorageTier)


@pytest.fixture
def armed_ledger():
    """Zero the process-global ledger (tables AND counters) around a
    test that asserts absolute counter values, then restore the suite's
    `record` default (primed by conftest's env conf)."""
    prior = ledger.mode()
    ledger.reset()
    yield ledger
    ledger.reset()
    ledger.install(prior)


def _batch(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return ColumnarBatch.from_pydict({
        "a": rng.integers(0, 1000, n),
        "b": rng.normal(size=n),
    })


# ---------------------------------------------------------------------------
# Modes
# ---------------------------------------------------------------------------

def test_install_modes_and_armed(armed_ledger):
    for m in ledger.MODES:
        ledger.install(m)
        assert ledger.mode() == m
        assert ledger.armed() == (m != "off")
    with pytest.raises(ValueError):
        ledger.install("banana")


def test_off_mode_tracks_nothing(armed_ledger):
    ledger.install("off")
    ledger.note_register(999001, 1024, 100.0, None)
    assert ledger.stats()["tracked"] == 0


def test_conf_refresh_primes_mode(armed_ledger):
    from spark_rapids_tpu import config as cfg
    conf = cfg.TpuConf()
    conf.set(cfg.ANALYSIS_BUFFER_LEDGER.key, "enforce")
    ledger.refresh(conf)
    assert ledger.mode() == "enforce"


# ---------------------------------------------------------------------------
# Lifecycle hooks: register / free / tombstones
# ---------------------------------------------------------------------------

def test_register_free_roundtrip_record(armed_ledger):
    ledger.install("record")
    cat = BufferCatalog.get()
    bid = cat.register_batch(_batch())
    assert ledger.stats()["tracked"] >= 1
    cat.remove(bid)
    # freed: tombstoned, not tracked; access in record mode only counts
    before = ledger.stats()["use_after_free"]
    ledger.note_access(bid)
    s = ledger.stats()
    assert s["use_after_free"] == before + 1


def test_use_after_free_raises_in_enforce(armed_ledger):
    ledger.install("enforce")
    cat = BufferCatalog.get()
    bid = cat.register_batch(_batch(seed=1))
    cat.remove(bid)
    with pytest.raises(UseAfterFreeError) as ei:
        cat.acquire_batch(bid)
    assert ei.value.buffer_id == bid
    assert "use-after-free" in str(ei.value)


def test_double_free_raises_in_enforce(armed_ledger):
    ledger.install("enforce")
    cat = BufferCatalog.get()
    bid = cat.register_batch(_batch(seed=2))
    cat.remove(bid)
    with pytest.raises(DoubleFreeError):
        cat.remove(bid)


def test_catalog_reset_is_not_a_free(armed_ledger):
    # test-teardown reset drops the tables WITHOUT tombstoning: a stale
    # handle probed by the next test must not diagnose use-after-free
    ledger.install("enforce")
    cat = BufferCatalog.get()
    bid = cat.register_batch(_batch(seed=3))
    BufferCatalog.reset()
    ledger.note_access(bid)              # unknown id now: silent
    assert ledger.stats()["use_after_free"] == 0


# ---------------------------------------------------------------------------
# Donation tombstones
# ---------------------------------------------------------------------------

def test_donated_batch_read_raises_in_enforce(armed_ledger):
    ledger.install("enforce")
    b = _batch(seed=4)
    b.flat_arrays()                      # pre-donation reads are fine
    ledger.mark_donated(b)
    assert ledger.stats()["donations"] == 1
    with pytest.raises(UseAfterDonateError) as ei:
        b.flat_arrays()
    assert "use-after-donate" in str(ei.value)
    with pytest.raises(UseAfterDonateError):
        b.fetch_to_host()


def test_donated_batch_read_counts_in_record(armed_ledger):
    ledger.install("record")
    b = _batch(seed=5)
    ledger.mark_donated(b)
    b.flat_arrays()                      # continues (arrays still live
    #                                      on the CPU test backend)
    assert ledger.stats()["use_after_donate"] == 1


def test_mark_donated_noop_when_disarmed(armed_ledger):
    ledger.install("off")
    b = _batch(seed=6)
    ledger.mark_donated(b)
    assert b.donated is None
    b.flat_arrays()


# ---------------------------------------------------------------------------
# End-of-query residency audit
# ---------------------------------------------------------------------------

def test_end_of_query_flags_leak_and_enforce_raises(armed_ledger):
    ledger.install("enforce")
    cat = BufferCatalog.get()
    qid = "qtest-leak-1"
    with qc.query_scope(qc.QueryContext(qid)):
        bid = cat.register_batch(_batch(seed=7))
    try:
        with pytest.raises(BufferLeakError) as ei:
            ledger.end_of_query(qid)
        assert ei.value.query_id == qid
        assert "leaked" in str(ei.value)
        assert ledger.stats()["leaks"] == 1
        # the leak is disowned after one report: a second audit is clean
        assert ledger.end_of_query(qid) is None or \
            ledger.end_of_query(qid)["leakedBuffers"] == 0
    finally:
        cat.remove(bid)


def test_end_of_query_record_reports_without_raising(armed_ledger):
    ledger.install("record")
    cat = BufferCatalog.get()
    qid = "qtest-leak-2"
    with qc.query_scope(qc.QueryContext(qid)):
        bid = cat.register_batch(_batch(seed=8))
    try:
        rep = ledger.end_of_query(qid)
        assert rep["leakedBuffers"] == 1
        assert rep["leakedBytes"] > 0
        assert rep["sites"]
    finally:
        cat.remove(bid)


def test_end_of_query_clean_when_freed(armed_ledger):
    ledger.install("enforce")
    cat = BufferCatalog.get()
    qid = "qtest-clean"
    with qc.query_scope(qc.QueryContext(qid)):
        bid = cat.register_batch(_batch(seed=9))
        cat.remove(bid)
    rep = ledger.end_of_query(qid)
    assert rep["leakedBuffers"] == 0
    assert rep["mintedBuffers"] == 1
    assert rep["peakDeviceBytes"] > 0


def test_end_of_query_cache_and_spilled_exempt(armed_ledger):
    # deliberate ownership transfers are not leaks: cache-priority
    # registrations (df.cache(), scan cache) and buffers no longer
    # device-resident
    ledger.install("enforce")
    cat = BufferCatalog.get()
    qid = "qtest-exempt"
    with qc.query_scope(qc.QueryContext(qid)):
        cached = cat.register_batch(_batch(seed=10),
                                    priority=CACHE_PRIORITY)
        spilled = cat.register_batch(_batch(seed=11))
        cat.buffers[spilled].spill_to_host()
    try:
        rep = ledger.end_of_query(qid)
        assert rep["leakedBuffers"] == 0
    finally:
        cat.remove(cached)
        cat.remove(spilled)


def test_end_of_query_had_error_downgrades_enforce(armed_ledger):
    ledger.install("enforce")
    cat = BufferCatalog.get()
    qid = "qtest-had-error"
    with qc.query_scope(qc.QueryContext(qid)):
        bid = cat.register_batch(_batch(seed=12))
    try:
        rep = ledger.end_of_query(qid, had_error=True)   # must not raise
        assert rep["leakedBuffers"] == 1
    finally:
        cat.remove(bid)


def test_tier_moves_update_peak_device_bytes(armed_ledger):
    ledger.install("record")
    cat = BufferCatalog.get()
    qid = "qtest-tier"
    with qc.query_scope(qc.QueryContext(qid)):
        bid = cat.register_batch(_batch(seed=13))
        buf = cat.buffers[bid]
        nbytes = buf.size_bytes
        buf.spill_to_host()
        assert buf.tier == StorageTier.HOST
    try:
        rep = ledger.end_of_query(qid)
        assert rep["peakDeviceBytes"] >= nbytes
        assert rep["leakedBuffers"] == 0   # host-resident: not a leak
    finally:
        cat.remove(bid)


def test_spillable_handle_close_is_a_clean_free(armed_ledger):
    ledger.install("enforce")
    qid = "qtest-handle"
    with qc.query_scope(qc.QueryContext(qid)):
        handle = SpillableColumnarBatch(_batch(seed=14))
        handle.close()
        handle.close()                   # idempotent by contract: the
        #                                  _closed guard never reaches
        #                                  remove twice
    rep = ledger.end_of_query(qid)
    assert rep["leakedBuffers"] == 0
    assert ledger.stats()["double_free"] == 0


# ---------------------------------------------------------------------------
# Acceptance: q3-shaped 3-way join under enforce + lockdep enforce
# ---------------------------------------------------------------------------

def test_q3_three_way_join_under_enforce_watermarks_zero():
    from benchmarks import datagen, queries as Q
    from spark_rapids_tpu.analysis import lockdep
    from spark_rapids_tpu.api.session import TpuSession
    # session bootstrap primes both audits from its conf (the
    # test_service / test_compile_pool pattern for enforce runs)
    session = TpuSession.builder.config({
        "spark.rapids.tpu.sql.explain": "NONE",
        "spark.rapids.tpu.sql.analysis.bufferLedger": "enforce",
        "spark.rapids.tpu.sql.analysis.lockdep": "enforce",
    }).getOrCreate()
    assert ledger.mode() == "enforce"
    try:
        tables = datagen.register_tables(session, 0.002)
        rows = Q.QUERIES["q3"](tables).collect_batch() \
            .fetch_to_host().rows()
        assert len(rows) <= 10           # top-N query
        led = session._last_ledger
        assert led is not None, "audit must run under enforce"
        assert led["leakedBuffers"] == 0
        assert led["mintedBuffers"] >= 0
        # tenant watermarks back at zero: no query-owned device bytes
        # outlive the collect (the test_service discipline)
        assert BufferCatalog.get().tenant_device_bytes() == {}
    finally:
        ledger.install("record")
        lockdep.refresh_mode("record")


def test_session_bootstrap_primes_ledger_from_conf():
    from spark_rapids_tpu.api.session import TpuSession
    TpuSession.builder.config(
        {"spark.rapids.tpu.sql.analysis.bufferLedger": "enforce"}
    ).getOrCreate()
    try:
        assert ledger.mode() == "enforce"
    finally:
        ledger.install("record")
    # a later session without the key re-primes from its own conf
    # (conftest's env default: record)
    TpuSession.builder.getOrCreate()
    assert ledger.mode() == "record"
