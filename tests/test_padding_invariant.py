"""Regression tests: every expression output must keep padding rows invalid and
zeroed (DESIGN.md §1 invariant), so filters over predicate outputs can't leak
padding rows as live data.
"""

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.ops import kernels as K
from spark_rapids_tpu.ops import predicates as P
from spark_rapids_tpu.ops.expressions import col, lit


def _resolve(expr, batch):
    return expr.transform(
        lambda e: e.resolve(batch.schema) if hasattr(e, "resolve") else None)


def _assert_padding_clean(column, num_rows):
    valid = np.asarray(column.validity)
    data = np.asarray(column.data)
    assert not valid[num_rows:].any(), "padding rows must be invalid"
    assert not data[num_rows:].any(), "padding rows must be zeroed"


def test_not_is_null_filter_does_not_leak_padding():
    b = ColumnarBatch.from_pydict({"x": [1, None, 3]})
    pred = _resolve(P.Not(P.IsNull(col("x"))), b)
    out = pred.eval(b)
    _assert_padding_clean(out, b.num_rows)
    keep = np.asarray(out.data) & np.asarray(out.validity)
    import jax.numpy as jnp
    [compacted], count = K.compact_columns([b.column("x")], jnp.asarray(keep))
    assert int(count) == 2
    assert compacted.to_pylist(2) == [1, 3]


def test_predicate_padding_clean():
    b = ColumnarBatch.from_pydict({"x": [1.0, None, float("nan")]})
    for expr in [P.IsNull(col("x")), P.IsNotNull(col("x")), P.IsNaN(col("x")),
                 P.EqualNullSafe(col("x"), lit(1.0)),
                 P.Not(P.EqualNullSafe(col("x"), col("x")))]:
        out = _resolve(expr, b).eval(b)
        _assert_padding_clean(out, b.num_rows)


def test_from_pydict_respects_schema_order():
    schema = dt.Schema([("a", dt.INT64), ("b", dt.INT64)])
    b = ColumnarBatch.from_pydict({"b": [10, 20], "a": [1, 2]}, schema=schema)
    assert b.to_pydict() == {"a": [1, 2], "b": [10, 20]}


def test_cast_timestamp_honors_utc_offset():
    from spark_rapids_tpu.ops.cast import _parse_value
    base = _parse_value("2020-01-01 00:00:00", dt.TIMESTAMP)
    offset = _parse_value("2020-01-01 00:00:00+05:00", dt.TIMESTAMP)
    assert base - offset == 5 * 3600 * 1_000_000


def test_nullif_semantics():
    from spark_rapids_tpu.ops import conditionals as cond
    b = ColumnarBatch.from_pydict({"y": [1, 20, None], "z": ["a", "", None]})
    out = _resolve(cond.NullIf(col("y"), lit(20)), b).eval(b)
    assert out.to_pylist(3) == [1, None, None]
    # null b never matches (nullif(a, NULL) = a)
    out2 = _resolve(cond.NullIf(col("y"), lit(None, dt.INT64)), b).eval(b)
    assert out2.to_pylist(3) == [1, 20, None]
    # string path: empty string vs null must not be conflated
    out3 = _resolve(cond.NullIf(col("z"), lit("")), b).eval(b)
    assert out3.to_pylist(3) == ["a", None, None]
