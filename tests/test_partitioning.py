"""Partitioning + shuffle tests.

Reference analog: GpuPartitioningSuite + repart_test (SURVEY.md §4).
"""

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.ops.expressions import BoundReference
from spark_rapids_tpu.shuffle.partitioning import (HashPartitioner,
                                                   RoundRobinPartitioner,
                                                   SinglePartitioner)


def _batch(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return ColumnarBatch.from_pydict({
        "k": [None if rng.random() < 0.05 else int(x)
              for x in rng.integers(0, 50, n)],
        "v": [float(x) for x in rng.normal(size=n)],
        "s": [f"s{x}" for x in rng.integers(0, 10, n)],
    })


def test_hash_partition_exhaustive_and_disjoint():
    b = _batch(200)
    p = HashPartitioner(4, [BoundReference(0, dt.INT64)])
    parts = p.split(b)
    assert len(parts) == 4
    total = sum(x.num_rows for x in parts)
    assert total == 200
    # same key always lands in the same partition
    key_home = {}
    for pi, part in enumerate(parts):
        for k in part.to_pydict()["k"]:
            if k in key_home:
                assert key_home[k] == pi, f"key {k} split across partitions"
            key_home[k] = pi


def test_hash_partition_deterministic_spark_placement():
    # pmod(murmur3(k, 42), n) — verified against the murmur3 reference impl
    b = ColumnarBatch.from_pydict({"k": [0, 42, -1]})
    p = HashPartitioner(3, [BoundReference(0, dt.INT64)])
    import numpy as np
    pids = np.asarray(p.partition_ids(b))[:3]
    from test_strings import _ref_bytes, _fmix, _mixh1, _mixk1, _s32

    def ref_long(v, seed=42):
        M = 0xFFFFFFFF
        lv = v & 0xFFFFFFFFFFFFFFFF
        h1 = _mixh1(seed, _mixk1(lv & M))
        h1 = _mixh1(h1, _mixk1((lv >> 32) & M))
        return _s32(_fmix(h1, 8))

    for val, pid in zip([0, 42, -1], pids):
        # Spark pmod: ((h % n) + n) % n (python % on ints already gives this)
        assert pid == ref_long(val) % 3


def test_round_robin_balance():
    b = _batch(100)
    p = RoundRobinPartitioner(4)
    parts = p.split(b)
    sizes = [x.num_rows for x in parts]
    assert sum(sizes) == 100
    assert max(sizes) - min(sizes) <= 1


def test_single_partitioner():
    b = _batch(10)
    parts = SinglePartitioner().split(b)
    assert len(parts) == 1 and parts[0].num_rows == 10


def test_split_preserves_data():
    b = _batch(123, seed=5)
    p = HashPartitioner(5, [BoundReference(0, dt.INT64)])
    parts = p.split(b)
    orig = sorted(zip(*[b.to_pydict()[c] for c in ("k", "v", "s")]),
                  key=repr)
    got = []
    for part in parts:
        d = part.to_pydict()
        got.extend(zip(d["k"], d["v"], d["s"]))
    assert sorted(got, key=repr) == orig


def test_exchange_exec_roundtrip():
    from spark_rapids_tpu.plan.physical import TpuLocalScanExec
    from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.ops.expressions import ColumnRef
    b = _batch(200, seed=9)
    scan = TpuLocalScanExec(b.to_arrow(), b.schema)
    ex = TpuShuffleExchangeExec(scan, 4,
                                [ColumnRef("k").resolve(b.schema)])
    parts = ex.execute()
    assert len(parts) == 4
    rows = []
    for p in parts:
        for batch in p:
            d = batch.to_pydict()
            rows.extend(zip(d["k"], d["v"], d["s"]))
    orig = list(zip(*[b.to_pydict()[c] for c in ("k", "v", "s")]))
    assert sorted(rows, key=repr) == sorted(orig, key=repr)


def test_split_deferred_matches_blocking():
    """The fused deferred split must produce exactly the blocking
    split's pieces once its counts resolve."""
    b = _batch(150, seed=3)
    p = HashPartitioner(4, [BoundReference(0, dt.INT64)])
    blocking = p.split(b)
    counts, make_pieces = p.split_deferred(b)
    pieces = make_pieces(np.asarray(counts))
    assert len(pieces) == len(blocking) == 4
    for got, want in zip(pieces, blocking):
        assert got.num_rows == want.num_rows
        assert got.to_pydict() == want.to_pydict()


def test_split_deferred_degraded_resolve_rereads():
    """make_pieces(None) — the PipelineWindow degraded-resolve contract —
    re-reads the counts itself and still yields correct pieces."""
    b = _batch(80, seed=8)
    p = HashPartitioner(3, [BoundReference(0, dt.INT64)])
    _counts, make_pieces = p.split_deferred(b)
    pieces = make_pieces(None)
    assert sum(x.num_rows for x in pieces) == 80


def test_split_deferred_through_pipeline_window():
    """Deferred splits ride the window: pushes stay pending until the
    depth fills, the flush lands everything, and the landed pieces
    round-trip all rows."""
    from spark_rapids_tpu.exec.pipeline import PipelineWindow
    p = HashPartitioner(4, [BoundReference(0, dt.INT64)])
    win = PipelineWindow(8)
    landed = []
    batches = [_batch(64, seed=s) for s in range(3)]
    for b in batches:
        counts, make_pieces = p.split_deferred(b)
        win.push(lambda hc, mk=make_pieces: landed.append(mk(hc)), counts)
    assert len(landed) == 0          # nothing resolved yet: all in flight
    win.flush()
    assert len(landed) == 3
    assert win.resolves <= 2         # packed, not one readback per batch
    got = sorted((r for pieces in landed for piece in pieces
                  for r in zip(*[piece.to_pydict()[c]
                                 for c in ("k", "v", "s")])), key=repr)
    exp = sorted((r for b in batches
                  for r in zip(*[b.to_pydict()[c]
                                 for c in ("k", "v", "s")])), key=repr)
    assert got == exp


def test_single_partitioner_has_nothing_to_defer():
    b = _batch(10)
    assert SinglePartitioner().split_deferred(b) is None


def test_round_robin_pick_index_cached():
    """The device pick-index array is cached per (capacity,
    num_partitions, start) — repeated batches reuse the same device
    array instead of rebuilding it."""
    from spark_rapids_tpu.shuffle.partitioning import _RR_IDX_CACHE
    _RR_IDX_CACHE.clear()
    p = RoundRobinPartitioner(4)
    b1, b2 = _batch(100), _batch(100, seed=1)
    ids1 = p.partition_ids(b1)
    ids2 = p.partition_ids(b2)
    assert ids1 is ids2              # same cached device array
    assert len(_RR_IDX_CACHE) == 1
    # a different partition count is a different cache entry
    RoundRobinPartitioner(3).partition_ids(b1)
    assert len(_RR_IDX_CACHE) == 2


def test_mesh_distributed_groupby():
    """SPMD all_to_all groupby on the virtual 8-device mesh (the
    dryrun_multichip path as a unit test)."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import importlib
    ge = importlib.import_module("__graft_entry__")
    ge.dryrun_multichip(8)
