"""The shared deferred-scalar pipeline window (exec/pipeline.py) and the
pipelined join stream loop built on it.

Reference analog: the per-batch join stream loop with no host sync
(GpuHashJoin.scala:193-249) and the streaming aggregate's in-flight batch
window (aggregate.scala:427-485). On high-latency links the engine's perf
metric of record is the attributed host-sync count (exec/tracing.py), so
these tests pin the O(1)-syncs-per-stage contract, not wall time.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.exec.pipeline import PipelineWindow
from spark_rapids_tpu.exec.tracing import SpanRecorder, SyncCounter, trace_span
from spark_rapids_tpu.ops import expressions as ex
from spark_rapids_tpu.ops import predicates as pr
from spark_rapids_tpu.plan import logical as lp
from spark_rapids_tpu.plan.physical import (TpuFilterExec, TpuLocalScanExec,
                                            TpuSortMergeJoinExec)


# ---------------------------------------------------------------------------
# PipelineWindow unit behavior
# ---------------------------------------------------------------------------

def test_depth1_degenerates_to_blocking():
    """depth=1: every push lands its own entry immediately — today's
    read-per-batch cadence, no behavior change."""
    win = PipelineWindow(1)
    out = win.push(lambda v: ("r", int(v)), jnp.int32(7))
    assert out == [("r", 7)]
    assert len(win) == 0
    assert win.flush() == []
    assert win.resolves == 1


def test_window_fills_then_lands_oldest_half():
    win = PipelineWindow(4)
    res = []
    for i in range(3):
        res += win.push(lambda v, i=i: (i, int(v)), jnp.int32(i * 10))
    assert res == []                      # window not yet full: no readback
    assert win.resolves == 0
    res += win.push(lambda v: (3, int(v)), jnp.int32(30))
    assert res == [(0, 0), (1, 10)]       # oldest half landed, FIFO
    assert win.resolves == 1              # ... in ONE batched resolve
    res += win.flush()                    # partition end: drain the rest
    assert res == [(0, 0), (1, 10), (2, 20), (3, 30)]
    assert len(win) == 0


def test_partition_end_flush_empty_window():
    assert PipelineWindow(8).flush() == []


def test_scalar_free_entries_ride_through():
    """Entries with no scalars (semi/anti joins) run immediately when
    nothing older is pending — scalar-free streams stay incremental — but
    queue FIFO behind an in-flight scalar entry."""
    win = PipelineWindow(8)
    assert win.push(lambda: "now") == ["now"]
    assert win.push(lambda v: int(v), jnp.int32(5)) == []
    assert win.push(lambda: "later") == []     # FIFO: must not overtake
    assert win.flush() == [5, "later"]


def test_mixed_dtypes_arrays_and_host_values():
    """int32 scalars, float64 stat vectors, and host values resolve in one
    landing; array shapes survive the packed transfer; no cross-dtype cast
    (counts never round-trip through a float)."""
    win = PipelineWindow(4)
    stats = jnp.asarray([3.0, 1.5e9], dtype=jnp.float64)
    got = []
    win.push(lambda a, b, c: got.append((a, b, c)),
             jnp.int32(1 << 25), stats, 42)
    win.push(lambda v: got.append(v), jnp.int32(2))
    win.flush()
    a, b, c = got[0]
    assert int(a) == 1 << 25              # > 2^24: would corrupt via f32
    assert b.shape == (2,) and np.allclose(np.asarray(b), [3.0, 1.5e9])
    assert c == 42                        # host value passes through
    assert int(got[1]) == 2


def test_batched_resolve_is_one_sync_per_dtype():
    """k same-dtype pending scalars cost ONE attributed host sync (the
    packed-concat read), not k — the whole point of the window."""
    win = PipelineWindow(16)
    for i in range(8):
        win.push(lambda v, i=i: int(v), jnp.int32(i) + jnp.int32(1))
    with SyncCounter() as sc:
        out = win.flush()
    assert out == [i + 1 for i in range(8)]
    assert sc.total <= 2, sc.sites        # packed read (+ slack), not 8


# ---------------------------------------------------------------------------
# Pipelined join stream loop (exec level)
# ---------------------------------------------------------------------------

def _scan(df: pd.DataFrame, batch_rows: int):
    table = pa.Table.from_pandas(df, preserve_index=False)
    schema = dt.Schema([dt.Field(f.name, dt.from_arrow(f.type), f.nullable)
                        for f in table.schema])
    return TpuLocalScanExec(table, schema, batch_rows=batch_rows)


def _collect_rows(exec_node):
    rows = []
    for part in exec_node.execute():
        for batch in part:
            d = batch.to_pydict()
            rows.extend(zip(*[d[n] for n in d.keys()]))
    exec_node.cleanup()
    return rows


def _join_exec(ldf, rdf, how, lkey, rkey, depth, batch_rows=1024,
               stream_filter=None):
    left = _scan(ldf, batch_rows)
    if stream_filter is not None:
        left = TpuFilterExec(left, stream_filter)
    j = TpuSortMergeJoinExec(left, _scan(rdf, 1 << 20), how,
                             [ex.ColumnRef(lkey)], [ex.ColumnRef(rkey)])
    j.pipeline_depth = depth
    return j


@pytest.fixture
def join_frames():
    rng = np.random.default_rng(11)
    n = 8192                              # 8 stream batches at 1024 rows
    left = pd.DataFrame({"k": rng.integers(0, 300, n).astype("int64"),
                         "v": rng.normal(0, 10, n)})
    right = pd.DataFrame({"rk": np.arange(250, dtype="int64"),
                          "w": rng.normal(0, 1, 250)})
    return left, right


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_pipelined_join_matches_depth1(join_frames, how):
    """Every join family produces identical rows at depth=1 (blocking,
    today's behavior) and a deep window (pipelined)."""
    left, right = join_frames
    r1 = sorted(_collect_rows(_join_exec(left, right, how, "k", "rk", 1)),
                key=repr)
    r16 = sorted(_collect_rows(_join_exec(left, right, how, "k", "rk", 16)),
                 key=repr)
    assert r1 == r16
    # pandas oracle for the inner case
    if how == "inner":
        exp = left.merge(right, left_on="k", right_on="rk")
        assert len(r16) == len(exp)


def _join_path_syncs(sc: SyncCounter) -> int:
    """Syncs attributed to the join/pipeline machinery (the collection
    helper's own per-batch to_pydict reads are not the join path)."""
    return sum(v for site, v in sc.sites.items()
               if "exec/pipeline.py" in site or "plan/physical.py" in site
               or "ops/joins.py" in site)


def test_pipelined_join_fewer_syncs_than_blocking(join_frames):
    """The pipelined window must collapse the per-batch sizing readbacks:
    8 stream batches at depth 16 resolve in O(1) batched reads vs 8
    blocking reads at depth 1."""
    left, right = join_frames
    j1 = _join_exec(left, right, "inner", "k", "rk", 1)
    with SyncCounter() as sc1:
        n1 = len(_collect_rows(j1))
    j16 = _join_exec(left, right, "inner", "k", "rk", 16)
    with SyncCounter() as sc16:
        n16 = len(_collect_rows(j16))
    assert n1 == n16 > 0
    # depth 1 = one blocking sizing read per stream batch; the window
    # collapses them to O(1) per stage
    assert _join_path_syncs(sc1) >= 8, sc1.sites
    assert _join_path_syncs(sc16) <= 2, sc16.sites


def test_pipelined_join_empty_batch_flow(join_frames):
    """Batches a filter emptied (device-resident zero counts) flow through
    the window without wedging it or emitting phantom rows."""
    left, right = join_frames
    # keep only k < 30: most 1024-row batches still match something, but
    # shrink right so several batches join to nothing
    cond = pr.LessThan(ex.ColumnRef("k"), ex.lit(30))
    j = _join_exec(left, right, "inner", "k", "rk", 16,
                   stream_filter=cond)
    rows = _collect_rows(j)
    exp = left[left.k < 30].merge(right, left_on="k", right_on="rk")
    assert len(rows) == len(exp)
    got_keys = sorted(r[0] for r in rows)
    assert got_keys == sorted(exp.k.tolist())


def test_full_outer_unmatched_tail_through_window(join_frames):
    """Full outer: the unmatched-build tail rides the pipelined path with
    a device-resident count (no per-stage blocking tail readback)."""
    left, right = join_frames
    # right keys 0..249, left keys 0..299: some right rows unmatched too
    lsmall = left[left.k >= 50].reset_index(drop=True)   # right 0..49 unmatched
    j = _join_exec(lsmall, right, "full", "k", "rk", 16)
    rows = _collect_rows(j)
    exp = lsmall.merge(right, left_on="k", right_on="rk", how="outer")
    assert len(rows) == len(exp)
    # unmatched build rows came out with NULL left columns
    null_left = [r for r in rows if r[0] is None]
    assert len(null_left) == 50
    assert sorted(r[2] for r in null_left) == list(range(50))


# ---------------------------------------------------------------------------
# Session-level: q3-shaped multi-join host syncs are O(1) per stage
# ---------------------------------------------------------------------------

def _q3_frames():
    rng = np.random.default_rng(5)
    n = 16384
    line = pd.DataFrame({
        "l_order": rng.integers(0, 2000, n).astype("int64"),
        "l_price": rng.normal(100.0, 10.0, n)})
    orders = pd.DataFrame({
        "o_key": np.arange(2000, dtype="int64"),
        "o_cust": rng.integers(0, 150, 2000).astype("int64"),
        "o_date": rng.integers(0, 1000, 2000).astype("int64")})
    cust = pd.DataFrame({
        "c_key": np.arange(150, dtype="int64"),
        "c_seg": rng.integers(0, 3, 150).astype("int64")})
    return line, orders, cust


def _run_q3(line, orders, cust, batch_rows):
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.config({
        "spark.rapids.tpu.sql.explain": "NONE",
        "spark.rapids.tpu.sql.reader.batchSizeRows": batch_rows,
    }).getOrCreate()
    s.createDataFrame(line).createOrReplaceTempView("q3_lineitem")
    s.createDataFrame(orders).createOrReplaceTempView("q3_orders")
    s.createDataFrame(cust).createOrReplaceTempView("q3_customer")
    df = s.sql(
        "SELECT l_price, o_date, c_seg FROM q3_lineitem "
        "JOIN q3_orders ON l_order = o_key "
        "JOIN q3_customer ON o_cust = c_key "
        "WHERE o_date < 700 AND c_seg = 1")
    rows = df.collect()
    return rows, s.last_query_metrics()["sync"]


def test_q3_shaped_multi_join_host_syncs_o1_per_stage():
    """Acceptance: a q3-shaped 3-way join at multi-batch scale shows
    join-path host syncs ~O(1) per stage in last_query_metrics()['sync'],
    not one blocking readback per stream batch (VERDICT r5: 16 of q3's 51
    syncs were the per-batch join-size readback)."""
    line, orders, cust = _q3_frames()
    rows_one, sync_one = _run_q3(line, orders, cust, 1 << 20)  # 1 batch
    rows_many, sync_many = _run_q3(line, orders, cust, 1024)   # 16 batches
    assert sorted(rows_one, key=repr) == sorted(rows_many, key=repr)
    # pandas oracle
    exp = (line.merge(orders, left_on="l_order", right_on="o_key")
               .merge(cust, left_on="o_cust", right_on="c_key"))
    exp = exp[(exp.o_date < 700) & (exp.c_seg == 1)]
    assert len(rows_many) == len(exp)
    # join-path sizing resolves attribute to the pipeline window; they
    # must stay O(1) per stage at 16x the batch count
    pipeline_syncs = sum(
        v for site, v in sync_many["syncSites"].items()
        if "exec/pipeline.py" in site)
    assert pipeline_syncs <= 4, sync_many["syncSites"]
    # and totals must not scale with the batch count (16x batches; a
    # per-batch readback regression would add ~15+ syncs per stage)
    assert sync_many["hostSyncs"] <= sync_one["hostSyncs"] + 12, \
        (sync_one, sync_many)


# ---------------------------------------------------------------------------
# SpanRecorder: generator-suspended spans close out of order
# ---------------------------------------------------------------------------

def test_span_recorder_out_of_order_close_keeps_attribution():
    """A span held open across a generator yield closes while a younger
    span is still open; its self-time must be its own, and it must not
    steal the younger frame off the stack (the old unconditional pop)."""
    import time
    rec = SpanRecorder()
    with rec:
        def gen():
            with trace_span("g_span"):
                yield
        g = gen()
        next(g)
        with trace_span("outer"):
            time.sleep(0.05)
            next(g, None)         # g_span closes under outer
            time.sleep(0.01)
    rep = rec.report()
    assert rep["g_span"]["count"] == 1
    assert rep["outer"]["count"] == 1
    # old behavior: g_span's close popped OUTER's frame and credited the
    # elapsed time to g_span's own frame, zeroing g_span's self-time
    assert rep["g_span"]["selfS"] >= 0.04
    assert rep["outer"]["selfS"] >= 0.04


def test_span_recorder_add_feeds_report():
    rec = SpanRecorder()
    with rec:
        rec.add("external", 1.25)
        rec.add("external", 0.25)
    rep = rec.report()
    assert rep["external"]["count"] == 2
    assert rep["external"]["selfS"] == pytest.approx(1.5)


# ---------------------------------------------------------------------------
# Semaphore wait-vs-hold split
# ---------------------------------------------------------------------------

def test_semaphore_wait_hold_split_spans_and_stats():
    import time
    from spark_rapids_tpu.exec.device import TpuSemaphore
    sem = TpuSemaphore.initialize(1)
    rec = SpanRecorder()
    try:
        with rec:
            sem.acquire_if_necessary()
            time.sleep(0.02)
            sem.release_if_necessary()
        rep = rec.report()
        assert rep["semaphore_wait"]["count"] == 1
        assert rep["semaphore_hold"]["count"] == 1
        assert rep["semaphore_hold"]["selfS"] >= 0.015
        st = sem.stats()
        assert st["acquires"] == 1
        assert st["holdS"] >= 0.015
        assert st["waitS"] >= 0.0
    finally:
        TpuSemaphore.reset()


def test_semaphore_wait_measures_contention():
    import threading
    import time
    from spark_rapids_tpu.exec.device import TpuSemaphore
    sem = TpuSemaphore.initialize(1)
    try:
        sem.acquire_if_necessary()

        def worker():
            sem.acquire_if_necessary()
            sem.release_if_necessary()
        t = threading.Thread(target=worker)
        t.start()
        time.sleep(0.05)              # worker blocks on the held permit
        sem.release_if_necessary()
        t.join()
        st = sem.stats()
        assert st["acquires"] == 2
        assert st["waitS"] >= 0.04    # the worker's blocked time
    finally:
        TpuSemaphore.reset()
