"""Serving front door units (ISSUE 12, docs/plan_cache.md): plan
parameterization, the parameterized-plan cache, prepared statements,
the result cache's snapshot/invalidation, and the cached-binding
validation policy (analysis/contracts.validate_cached_binding)."""

import datetime

import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit


def _session(**conf):
    from spark_rapids_tpu.api.session import TpuSession
    base = {"spark.rapids.tpu.sql.explain": "NONE"}
    base.update(conf)
    return TpuSession.builder.config(base).getOrCreate()


def _dates_df(session):
    df = session.createDataFrame(pd.DataFrame({
        "d": pd.to_datetime(["1994-01-05", "1994-06-01",
                             "1995-02-01", "1995-07-07"]).date,
        "v": [1.0, 2.0, 3.0, 4.0]}))
    df.createOrReplaceTempView("t")
    return df


def _q6ish(df, lo, hi, qty):
    """q6-shaped: parameterizable filter chain folded under an agg."""
    return (df.filter((col("v") >= lit(lo)) & (col("v") < lit(hi)) &
                      (col("k") < lit(qty)))
            .agg(F.sum(col("v") * col("k")).alias("s")))


def _kv_df(session, n=512):
    return session.createDataFrame({
        "k": [i % 11 for i in range(n)],
        "v": [float(i) for i in range(n)]})


# ---------------------------------------------------------------------------
# Parameterization
# ---------------------------------------------------------------------------

def test_parameterize_extracts_filter_literals_and_slots_are_structural():
    import copy
    from spark_rapids_tpu.plan import logical as lp
    from spark_rapids_tpu.plan import plan_cache as pc
    from spark_rapids_tpu.ops import expressions as ex
    session = _session()
    df = _kv_df(session)

    def analyzed(lo, hi, qty):
        plan = copy.deepcopy(_q6ish(df, lo, hi, qty).logical_plan())
        return lp.analyze(plan)

    p1 = analyzed(1.0, 9.0, 5)
    params = pc.parameterize(p1)
    assert len(params) == 3
    assert [p.slot for p in params] == [0, 1, 2]
    assert all(isinstance(p, ex.Parameter) for p in params)
    f1 = pc.plan_fingerprint(p1)
    # different literal VALUES: identical fingerprint
    p2 = analyzed(3.0, 200.0, 8)
    pc.parameterize(p2)
    assert pc.plan_fingerprint(p2) == f1
    # different STRUCTURE: different fingerprint
    p3 = analyzed(1.0, 9.0, 5)
    p3 = lp.analyze(lp.Limit(p3, 7))
    pc.parameterize(p3)
    assert pc.plan_fingerprint(p3) != f1


def test_uncacheable_plans_fingerprint_none_but_run():
    from spark_rapids_tpu.plan import plan_cache as pc
    session = _session()
    df = _kv_df(session, 64)
    # nondeterministic expression: rand() plans must re-plan per run
    q = df.withColumn("r", F.rand(seed=7)).agg(F.sum("v").alias("s"))
    q.collect()
    assert session._last_serving["planCache"] == "uncacheable"
    assert session._last_serving["fingerprint"] is None
    q.collect()                      # still runs fine, still uncached
    assert pc.serving_stats(session)["planHits"] == 0


def test_plan_cache_hit_with_changed_literals_compiles_nothing():
    from spark_rapids_tpu.analysis import recompile
    session = _session()
    df = _kv_df(session)
    r1 = _q6ish(df, 1.0, 300.0, 6).collect()
    snap = recompile.snapshot()
    r2 = _q6ish(df, 2.0, 400.0, 9).collect()
    bad = {k: v for k, v in recompile.delta(snap).items()
           if v.get("compiles")}
    assert not bad, bad
    st = session.serving_stats()
    assert st["planHits"] == 1 and st["plansBuilt"] == 1, st
    assert r1 != r2                   # the literals really did change
    # oracle: fresh planning (cache off) agrees
    s2 = _session(**{"spark.rapids.tpu.sql.planCache.enabled": "false"})
    df2 = _kv_df(s2)
    assert _q6ish(df2, 2.0, 400.0, 9).collect() == r2


def test_param_traced_vs_eager_parity():
    """The fused (traced-argument) evaluation of a parameterized filter
    agrees with the per-op eager path."""
    session = _session()
    df = _kv_df(session)
    q = df.filter((col("v") >= lit(100.0)) & (col("k") < lit(7))) \
          .select((col("v") * lit(2.0)).alias("w"))
    fused = sorted(q.collect())
    s_off = _session(**{
        "spark.rapids.tpu.sql.wholeStageFusion.enabled": "false"})
    df_off = _kv_df(s_off)
    q_off = df_off.filter((col("v") >= lit(100.0)) & (col("k") < lit(7))) \
                  .select((col("v") * lit(2.0)).alias("w"))
    assert sorted(q_off.collect()) == fused


def test_conf_mutation_never_serves_a_stale_plan():
    from spark_rapids_tpu.plan.stage_compiler import TpuWholeStageExec
    session = _session()
    df = _kv_df(session)
    q = df.select((col("v") + lit(1.0)).alias("a"), col("k")) \
          .filter(col("a") > lit(10.0))
    q.collect()
    session.conf.set("spark.rapids.tpu.sql.fusion.wholeStage", "false")
    q.collect()

    def walk(n):
        yield n
        for c in n.children:
            yield from walk(c)
    assert not [n for n in walk(session.last_plan())
                if isinstance(n, TpuWholeStageExec)]


# ---------------------------------------------------------------------------
# Prepared statements
# ---------------------------------------------------------------------------

def test_prepared_statement_plans_once_executes_many():
    from spark_rapids_tpu.analysis import recompile
    session = _session()
    _dates_df(session)
    stmt = session.prepare(
        "SELECT sum(v) AS s FROM t WHERE d >= :lo AND d < :hi")
    assert stmt.parameter_names == ["hi", "lo"]
    r94 = stmt.collect(lo=datetime.date(1994, 1, 1),
                       hi=datetime.date(1995, 1, 1))
    assert r94 == [(3.0,)]
    snap = recompile.snapshot()
    r95 = stmt.collect(lo=datetime.date(1995, 1, 1),
                       hi=datetime.date(1996, 1, 1))
    assert r95 == [(7.0,)]
    bad = {k: v for k, v in recompile.delta(snap).items()
           if v.get("compiles")}
    assert not bad, bad
    st = session.serving_stats()
    # EXACTLY one parse / analyze / plan-build across both executions
    assert st["parses"] == 1 and st["analyzes"] == 1 and \
        st["plansBuilt"] == 1, st
    assert st["planHits"] >= 1, st
    # ISO strings bind as dates too
    assert stmt.collect(lo="1994-01-01", hi="1996-01-01") == [(10.0,)]


def test_prepared_statement_binding_errors():
    session = _session()
    _dates_df(session)
    stmt = session.prepare("SELECT sum(v) AS s FROM t WHERE v > :x")
    with pytest.raises(ValueError, match="missing"):
        stmt.execute()
    with pytest.raises(ValueError, match="unexpected"):
        stmt.execute(x=1.0, y=2.0)
    with pytest.raises(ValueError, match="NULL"):
        stmt.execute(x=None)


def test_prepared_statement_dtype_change_replans():
    session = _session()
    _dates_df(session)
    stmt = session.prepare("SELECT sum(v) AS s FROM t WHERE v > :x")
    assert stmt.collect(x=2)[0][0] == 7.0      # INT64 plan
    assert stmt.collect(x=2.5)[0][0] == 7.0    # FLOAT64: new fingerprint
    st = session.serving_stats()
    assert st["plansBuilt"] == 2, st
    # back to int: the first entry still serves
    assert stmt.collect(x=3)[0][0] == 4.0
    assert session.serving_stats()["plansBuilt"] == 2


def test_prepared_statement_param_in_unsupported_position_raises():
    session = _session()
    _dates_df(session)
    stmt = session.prepare("SELECT sum(v) AS s FROM t GROUP BY :g")
    with pytest.raises(ValueError, match="supported in WHERE"):
        stmt.execute(g=1)


def test_prepared_non_aggregate_select_works():
    """prepare() must not crash on non-aggregate SELECTs: the parser's
    schema probes analyze throwaway copies BEFORE the first bind, so an
    unbound placeholder types as NULLTYPE there (review finding)."""
    session = _session()
    _dates_df(session)
    stmt = session.prepare("SELECT v FROM t WHERE v > :x")
    assert sorted(stmt.collect(x=2.0)) == [(3.0,), (4.0,)]
    assert sorted(stmt.collect(x=3.0)) == [(4.0,)]
    star = session.prepare("SELECT * FROM t WHERE v > :x")
    assert len(star.collect(x=2.0)) == 2


def test_placeholders_correct_with_plan_cache_disabled():
    """With planCache.enabled=false, placeholders still get slots (an
    unslotted pair would collide on one fused-program key and silently
    serve a stale baked value — review finding)."""
    session = _session(**{"spark.rapids.tpu.sql.planCache.enabled":
                          "false"})
    session.createDataFrame({"v": [float(i) for i in range(10)]}) \
        .createOrReplaceTempView("nums")
    stmt = session.prepare(
        "SELECT sum(v) AS s FROM nums WHERE v >= :lo AND v < :hi")
    assert stmt.collect(lo=2.0, hi=5.0) == [(9.0,)]
    assert stmt.collect(lo=3.0, hi=8.0) == [(25.0,)]
    assert stmt.collect(lo=0.0, hi=10.0) == [(45.0,)]


def test_coerced_and_arith_wrapped_params_stay_fused(caplog):
    """The analyzer coerces placeholder dtypes with Casts (:q bound to a
    LONG against a DOUBLE column) and prepared trees keep arithmetic
    around placeholders (:d - 10.0). Both scalar folds run inside the
    fused trace, where their pure-numpy literal paths would concretize
    the traced parameter and silently degrade the whole stage to eager —
    they must compile into the program instead. Value-dependent-null
    folds (x / :z) can't, and must fall back with correct results."""
    import logging
    session = _session()
    session.createDataFrame({"v": [float(i) for i in range(100)]}) \
        .createOrReplaceTempView("nums")
    with caplog.at_level(logging.WARNING,
                         logger="spark_rapids_tpu.fusion"):
        stmt = session.prepare("SELECT sum(v) AS s FROM nums WHERE v < :q")
        assert stmt.collect(q=24)[0][0] == float(sum(range(24)))
        assert stmt.collect(q=30)[0][0] == float(sum(range(30)))
        arith = session.prepare("SELECT sum(v) AS s FROM nums "
                                "WHERE v >= :d - 10.0 AND v < :d + 10.0")
        assert arith.collect(d=30.0)[0][0] == float(sum(range(20, 40)))
        assert arith.collect(d=50.0)[0][0] == float(sum(range(40, 60)))
    eager = [r for r in caplog.records
             if "fell back to eager" in r.getMessage()]
    assert not eager, [r.getMessage() for r in eager]
    # div-by-param nullness depends on the traced value: eager, but right
    div = session.prepare("SELECT sum(v) AS s FROM nums WHERE v < 100.0 / :z")
    assert div.collect(z=2.0)[0][0] == float(sum(range(50)))
    assert div.collect(z=4.0)[0][0] == float(sum(range(25)))


def test_string_param_rebind_never_serves_stale_program():
    """Non-traceable (string) parameter values bake into the compiled
    programs AND the plan fingerprint, so the prepared fast path must
    NOT rebind a cached entry in place — the whole-stage exec's frozen
    program would serve the previous value's rows (review finding:
    m='RAIL' returned m='AIR' rows). Each distinct value gets its own
    plan-cache entry instead, which still hits on repeats."""
    session = _session()
    session.createDataFrame({
        "v": [1.0, 2.0, 3.0], "m": ["AIR", "RAIL", "AIR"]}) \
        .createOrReplaceTempView("ship")
    stmt = session.prepare("SELECT v FROM ship WHERE m = :m")
    assert sorted(stmt.collect(m="AIR")) == [(1.0,), (3.0,)]
    assert sorted(stmt.collect(m="RAIL")) == [(2.0,)]
    # flip back and forth: the per-value entries keep serving correctly
    assert sorted(stmt.collect(m="AIR")) == [(1.0,), (3.0,)]
    assert sorted(stmt.collect(m="RAIL")) == [(2.0,)]
    st = session.serving_stats()
    assert st["plansBuilt"] == 2 and st["planHits"] == 2, st


def test_result_hit_clears_span_recorder():
    """A result-cache hit runs nothing, so the session must not keep the
    PREVIOUS query's span recorder — a timeline export after the hit
    would attribute the old query's spans to this collect."""
    session = _session(**{
        "spark.rapids.tpu.sql.resultCache.enabled": "true"})
    df = _kv_df(session, 64)
    q = df.filter(col("v") >= lit(3.0)).agg(F.sum("v").alias("s"))
    q.collect()
    assert session._last_span_recorder is not None
    q.collect()                       # exact repeat: short-circuits
    assert session._last_serving["resultCache"] == "hit"
    assert session._last_span_recorder is None


def test_tainted_entry_discarded_after_error_mode_drift():
    """An error-mode drift raise must DISCARD the tainted entry so a
    clean retry replans instead of re-raising forever (review
    finding)."""
    from spark_rapids_tpu.analysis.contracts import PlanContractError
    from spark_rapids_tpu.columnar import dtypes as dt
    session = _session(**{
        "spark.rapids.tpu.sql.analysis.validatePlan": "error"})
    df = _kv_df(session)
    _q6ish(df, 1.0, 300.0, 6).collect()
    entry = _entry_for_last(session)
    entry.validated_dtypes = (dt.STRING,) + entry.validated_dtypes[1:]
    with pytest.raises(PlanContractError):
        _q6ish(df, 2.0, 300.0, 6).collect()
    # the retry replans cleanly (a poisoned entry would re-raise)
    r = _q6ish(df, 2.0, 300.0, 6).collect()
    assert r and session.serving_stats()["plansBuilt"] == 2


def test_prepared_dataframe_shares_the_plan_cache():
    session = _session()
    df = _kv_df(session)
    stmt = session.prepare(_q6ish(df, 1.0, 300.0, 6))
    r1 = stmt.execute().rows()
    r2 = stmt.execute().rows()
    assert r1 == r2
    st = session.serving_stats()
    assert st["plansBuilt"] == 1 and st["planHits"] >= 1, st


# ---------------------------------------------------------------------------
# Result cache
# ---------------------------------------------------------------------------

def test_result_cache_exact_repeat_short_circuits():
    session = _session(**{"spark.rapids.tpu.sql.resultCache.enabled":
                          "true"})
    df = _kv_df(session)
    q = _q6ish(df, 1.0, 300.0, 6)
    r1 = q.collect()
    r2 = q.collect()
    assert r1 == r2
    st = session.serving_stats()
    assert st["resultStores"] >= 1 and st["resultHits"] == 1, st
    # the serving line in EXPLAIN ANALYZE names the hit
    assert "resultCache=hit" in session.explain_analyze()
    # a different literal misses the result cache but hits the plan cache
    _q6ish(df, 2.0, 300.0, 6).collect()
    st = session.serving_stats()
    assert st["resultHits"] == 1 and st["planHits"] >= 2, st


def test_result_cache_invalidates_on_view_swap():
    session = _session(**{"spark.rapids.tpu.sql.resultCache.enabled":
                          "true"})
    _dates_df(session)
    q = "SELECT sum(v) AS s FROM t WHERE v > 0"
    assert session.sql(q).collect() == [(10.0,)]
    # new data under the same view name: a NEW base table identity, so
    # neither the plan fingerprint nor the result snapshot can alias
    df2 = session.createDataFrame({"d": [datetime.date(1994, 1, 2)],
                                   "v": [100.0]})
    df2.createOrReplaceTempView("t")
    assert session.sql(q).collect() == [(100.0,)]


def test_result_cache_byte_bound_and_entry_bound():
    from spark_rapids_tpu.plan.plan_cache import ResultCache
    rc = ResultCache(max_bytes=1000, max_entry_bytes=400)
    rc.put(("a",), "batch-a", 300)
    rc.put(("b",), "batch-b", 300)
    rc.put(("big",), "batch-big", 500)       # over maxEntryBytes: refused
    assert rc.get(("big",)) is None
    assert rc.get(("a",)) == "batch-a"
    rc.put(("c",), "batch-c", 300)
    rc.put(("d",), "batch-d", 300)           # evicts LRU (b)
    assert rc.get(("b",)) is None
    assert rc.bytes <= 1000


# ---------------------------------------------------------------------------
# Cached-binding validation (the contracts satellite)
# ---------------------------------------------------------------------------

def _entry_for_last(session):
    from spark_rapids_tpu.plan import plan_cache as pc
    cache, _rc = pc.session_caches(session)
    return cache.peek(session._last_serving["fingerprint"])


def test_binding_dtype_drift_retriggers_validation():
    from spark_rapids_tpu.columnar import dtypes as dt
    session = _session()
    df = _kv_df(session)
    _q6ish(df, 1.0, 300.0, 6).collect()
    st0 = session.serving_stats()
    assert st0["revalidations"] == 0
    entry = _entry_for_last(session)
    assert entry is not None and entry.params
    # seeded drift: pretend the entry was validated with another dtype
    # (a parameter substitution that changed a bound ref's dtype)
    entry.validated_dtypes = (dt.STRING,) + entry.validated_dtypes[1:]
    _q6ish(df, 2.0, 300.0, 6).collect()
    st = session.serving_stats()
    # the hit did NOT skip validation: the full walk re-ran, the tainted
    # entry was discarded, and the query replanned
    assert st["revalidations"] == 1, st
    assert st["plansBuilt"] == 2, st
    # the rebuilt entry serves clean hits again (validation skipped)
    _q6ish(df, 3.0, 300.0, 6).collect()
    st = session.serving_stats()
    assert st["revalidations"] == 1 and st["planHits"] >= 1, st


def test_binding_dtype_drift_error_mode_raises():
    from spark_rapids_tpu.analysis.contracts import PlanContractError
    from spark_rapids_tpu.columnar import dtypes as dt
    session = _session(**{
        "spark.rapids.tpu.sql.analysis.validatePlan": "error"})
    df = _kv_df(session)
    _q6ish(df, 1.0, 300.0, 6).collect()
    entry = _entry_for_last(session)
    entry.validated_dtypes = (dt.STRING,) + entry.validated_dtypes[1:]
    with pytest.raises(PlanContractError, match="rebound"):
        _q6ish(df, 2.0, 300.0, 6).collect()


def test_validate_cached_binding_unit():
    from spark_rapids_tpu.analysis import contracts as C
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.ops import expressions as ex

    class _Root:
        children = ()
    p = ex.Parameter(5, dt.INT64, slot=0)
    # clean binding: validation skipped
    reval, violations = C.validate_cached_binding(
        _Root(), [p], (dt.INT64,), "warn")
    assert not reval and not violations
    # drifted dtype: full revalidation with a drift violation
    reval, violations = C.validate_cached_binding(
        _Root(), [p], (dt.FLOAT64,), "warn")
    assert reval and any("rebound" in v.message for v in violations)
    # off mode: never validates
    assert C.validate_cached_binding(
        _Root(), [p], (dt.FLOAT64,), "off") == (False, [])


# ---------------------------------------------------------------------------
# Telemetry / EXPLAIN surfaces
# ---------------------------------------------------------------------------

def test_serving_counters_reach_the_metrics_registry():
    session = _session()
    df = _kv_df(session)
    _q6ish(df, 1.0, 300.0, 6).collect()
    _q6ish(df, 2.0, 300.0, 6).collect()
    text = session.prometheus_metrics()
    assert "tpu_plan_cache_hits_total" in text
    assert "tpu_plan_cache_misses_total" in text


def test_serving_series_ride_the_history_gate():
    """bench.py stamps plan_cache_plans_per_s (higher better) and
    warm_traffic_q6_s (lower better) into the regression gate."""
    from benchmarks import history as bh
    assert bh.WARM_TRAFFIC_Q6_S in bh.INVERTED_QUERIES
    assert bh.PLAN_CACHE_PLANS_PER_S not in bh.INVERTED_QUERIES
    entry = bh.round_entry(
        "bench", {bh.PLAN_CACHE_PLANS_PER_S: 80.0,
                  bh.WARM_TRAFFIC_Q6_S: 0.5}, backend="cpu")
    assert bh._hib_for(entry, bh.WARM_TRAFFIC_Q6_S) is False
    assert bh._hib_for(entry, bh.PLAN_CACHE_PLANS_PER_S) is True
    # a slower warm-traffic window FAILS against a faster baseline
    v = bh.verdict_for(1.0, 0.5, higher_is_better=False)
    assert v["verdict"] == "fail"


def test_explain_analyze_shows_serving_line():
    session = _session()
    df = _kv_df(session)
    _q6ish(df, 1.0, 300.0, 6).collect()
    out = session.explain_analyze()
    assert "serving: planCache=miss" in out
    _q6ish(df, 2.0, 300.0, 6).collect()
    out = session.explain_analyze()
    assert "serving: planCache=hit" in out and "params=3" in out
