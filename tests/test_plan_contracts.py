"""Plan-contract validator (analysis/contracts.py): clean real plans
validate with zero violations in every mode; seeded breakages are caught
in warn mode (explain-integrated diagnostic) and rejected in error mode.
"""

import pandas as pd
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.analysis import contracts
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.plan import physical as ph
from spark_rapids_tpu.plan.overrides import Overrides


@pytest.fixture()
def session():
    return TpuSession.builder.getOrCreate()


@pytest.fixture()
def df(session):
    return session.createDataFrame(pd.DataFrame({
        "k": [1, 2, 1, 3, 2, 2], "v": [1., 2., 3., 4., 5., 6.],
        "w": list("abcdef")}))


def _exec_plan(session, frame, **conf):
    ov = Overrides(session.conf.with_overrides(
        {"spark.rapids.tpu.sql.analysis.validatePlan": "error", **conf}))
    return ov, ov.apply(frame._analyzed())


def _find(node, klass):
    if isinstance(node, klass):
        return node
    for c in node.children:
        found = _find(c, klass)
        if found is not None:
            return found
    return None


# ---------------------------------------------------------------------------
# Clean plans: zero violations, even in error mode
# ---------------------------------------------------------------------------

def test_real_plans_validate_clean(session, df):
    df2 = session.createDataFrame(pd.DataFrame(
        {"k": [1, 2, 4], "u": [10., 20., 30.]}))
    shapes = [
        df.filter(F.col("v") > 1).select((F.col("v") * 2).alias("v2")),
        df.join(df2, on="k").groupBy("k").agg(F.sum("v").alias("sv")),
        df.orderBy("v").limit(3),
        df.select("k", "v").union(df.select("k", "v")).distinct(),
        df.repartition(3, "k").groupBy("k").agg(F.count("v").alias("c")),
    ]
    for frame in shapes:
        _ov, node = _exec_plan(session, frame)       # error mode: no raise
        assert contracts.validate_plan(node) == []


def test_every_converted_exec_declares_contract(session, df):
    _ov, node = _exec_plan(session, df.groupBy("k").agg(
        F.avg("v").alias("a")))

    def walk(n):
        assert type(n).CONTRACT is not None, type(n).__name__
        for c in n.children:
            walk(c)
    walk(node)


# ---------------------------------------------------------------------------
# Seeded breakages
# ---------------------------------------------------------------------------

def _corrupt_filter_schema(node):
    """Flip the filter's declared output dtypes (a passthrough exec lying
    about its schema — exactly the drift the validator exists to catch).
    The patched hook recurses with the conversion walk, so corrupt only
    when (and once) a filter is actually in this subtree."""
    filt = _find(node, ph.TpuFilterExec)
    if filt is not None and not getattr(filt, "_corrupted", False):
        filt._corrupted = True
        filt._schema = dt.Schema([dt.Field(f.name, dt.INT64, f.nullable)
                                  for f in filt._schema])
    return node


def test_seeded_schema_mismatch_warn_mode(session, df, monkeypatch):
    frame = df.filter(F.col("v") > 1).select("v")
    orig = Overrides._insert_coalesce
    monkeypatch.setattr(Overrides, "_insert_coalesce",
                        lambda self, n: _corrupt_filter_schema(orig(self, n)))
    # stage fusion off: the corruption targets the standalone filter exec,
    # which whole-stage fusion would otherwise collapse away
    ov = Overrides(session.conf.with_overrides(
        {"spark.rapids.tpu.sql.fusion.wholeStage": "false"}))
    # default mode: warn
    node = ov.apply(frame._analyzed())                # must NOT raise
    assert "contract" in ov.last_explain
    assert "TpuFilterExec" in ov.last_explain
    assert contracts.validate_plan(node) != []


def test_seeded_schema_mismatch_error_mode(session, df, monkeypatch):
    frame = df.filter(F.col("v") > 1).select("v")
    orig = Overrides._insert_coalesce
    monkeypatch.setattr(Overrides, "_insert_coalesce",
                        lambda self, n: _corrupt_filter_schema(orig(self, n)))
    ov = Overrides(session.conf.with_overrides(
        {"spark.rapids.tpu.sql.analysis.validatePlan": "error",
         "spark.rapids.tpu.sql.fusion.wholeStage": "false"}))
    with pytest.raises(contracts.PlanContractError) as ei:
        ov.apply(frame._analyzed())
    assert "TpuFilterExec" in str(ei.value)
    # the rejection diagnostic is explain-integrated
    assert "contract" in ov.last_explain


def test_off_mode_skips_validation(session, df, monkeypatch):
    frame = df.filter(F.col("v") > 1).select("v")
    orig = Overrides._insert_coalesce
    monkeypatch.setattr(Overrides, "_insert_coalesce",
                        lambda self, n: _corrupt_filter_schema(orig(self, n)))
    ov = Overrides(session.conf.with_overrides(
        {"spark.rapids.tpu.sql.analysis.validatePlan": "off"}))
    ov.apply(frame._analyzed())                       # no raise, no diag
    assert "contract" not in ov.last_explain


def test_bound_reference_drift_caught(session, df):
    _ov, node = _exec_plan(session, df.select((F.col("v") + 1).alias("x")))
    proj = _find(node, ph.TpuProjectExec)
    from spark_rapids_tpu.ops import expressions as ex
    refs = [r for e in proj.exprs
            for r in e.collect(lambda x: isinstance(x, ex.BoundReference))]
    assert refs
    refs[0].ordinal = 99                              # stale rebind
    violations = contracts.validate_plan(node)
    assert any("ordinal 99" in v.message for v in violations)


def test_missing_contract_detected(session, df):
    class TpuNoContractExec(ph.TpuExec):              # no CONTRACT on purpose
        @property
        def schema(self):
            return self.children[0].schema

        def execute(self):
            return self.children[0].execute()

    _ov, node = _exec_plan(session, df.select("v"))
    wrapped = TpuNoContractExec(node)
    violations = contracts.validate_plan(wrapped)
    assert any("no CONTRACT" in v.message for v in violations)


def test_distribution_invariant_final_agg(session, df):
    """A per-partition final merge demands the hash exchange below it."""
    _ov, node = _exec_plan(
        session, df.repartition(3, "k").groupBy("k").agg(
            F.sum("v").alias("s")))
    agg = _find(node, ph.TpuHashAggregateExec)
    assert agg is not None and agg.per_partition_final
    # sever the distribution: splice the exchange out from under the merge
    agg.children = [agg.children[0].children[0]]
    violations = contracts.validate_plan(node)
    assert any("non-exchange child" in v.message for v in violations)


def test_fallback_must_match_tagging_promise(session, df):
    from spark_rapids_tpu.plan.overrides import PlanMeta
    plan = df.select("v")._analyzed()
    meta = PlanMeta(plan, session.conf)
    meta.tag()
    assert meta.can_replace
    fallback = ph.CpuFallbackExec(plan)               # contradicts the promise
    violations = contracts.validate_plan(fallback, meta)
    assert any("contradicts the promise" in v.message for v in violations)
