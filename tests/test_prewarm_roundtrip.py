"""Cache prewarm across a process restart (ISSUE 17, docs/compile.md
§5): process A runs q6 cold against a compile-cache dir (recording the
prewarm corpus beside the signature index); a FRESH process B boots with
``compile.prewarm.enabled``, drains the background builds, then streams
the same q6 — and pays ZERO query-triggered stage compiles and zero cold
compiles of any family on the query thread. This is the acceptance pin
for the runner's ``cold_q6_s`` stamp honesty condition."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SF = "0.005"

# q6's tight filter folds into its aggregate kernel (a 'pre_stage'
# chain), so the workload pairs it with a pure filter+project scan that
# plans a standalone TpuWholeStageExec — the shape the prewarm corpus
# records and replays.
_SCAN_QUERY = r"""
def scan_query(tables):
    from spark_rapids_tpu.api.functions import col, lit
    return (tables["lineitem"]
            .filter((col("l_quantity") < lit(24))
                    & (col("l_discount") >= lit(0.05)))
            .select((col("l_extendedprice") * col("l_discount"))
                    .alias("rev"),
                    col("l_quantity")))
"""

_CHILD_A = _SCAN_QUERY + r"""
import json, sys
from spark_rapids_tpu.api.session import TpuSession
from benchmarks import datagen
from benchmarks import queries as Q
session = TpuSession.builder.config({
    "spark.rapids.tpu.sql.explain": "NONE",
    "spark.rapids.tpu.sql.compile.cacheDir": sys.argv[1]}).getOrCreate()
tables = datagen.register_tables(session, float(sys.argv[2]))
q6_rows = Q.QUERIES["q6"](tables).collect()
scan_rows = scan_query(tables).collect()
from spark_rapids_tpu.analysis import recompile
rep = recompile.report()
print(json.dumps({
    "q6Rows": len(q6_rows),
    "scanRows": len(scan_rows),
    "cold": sum(v["coldCompiles"] for v in rep.values()),
    "stageFamilies": sorted(k for k in rep if k.startswith("stage"))}))
"""

_CHILD_B = _SCAN_QUERY + r"""
import json, sys, time
from spark_rapids_tpu.api.session import TpuSession
from benchmarks import datagen
from benchmarks import queries as Q
session = TpuSession.builder.config({
    "spark.rapids.tpu.sql.explain": "NONE",
    "spark.rapids.tpu.sql.compile.cacheDir": sys.argv[1],
    "spark.rapids.tpu.sql.compile.prewarm.enabled": "true"}).getOrCreate()
from spark_rapids_tpu.exec import compile_pool
from spark_rapids_tpu.plan import aqe
drained = compile_pool.drain(timeout_s=300.0)
stats = compile_pool.stats()
tables = datagen.register_tables(session, float(sys.argv[2]))
from spark_rapids_tpu.analysis import recompile
snap = recompile.snapshot()
t0 = time.perf_counter()
first = None
scan_rows = []
for b in scan_query(tables).collect_iter():
    if first is None:
        first = time.perf_counter() - t0
    scan_rows.extend(b.rows())
q6_rows = Q.QUERIES["q6"](tables).collect()
d = recompile.delta(snap)
print(json.dumps({
    "q6Rows": len(q6_rows),
    "scanRows": len(scan_rows),
    "drained": bool(drained),
    "prewarmBuilt": stats.get("prewarmBuilt", 0),
    "failed": stats.get("failed", 0),
    "stageCompiles": sum(v.get("compiles", 0) for k, v in d.items()
                         if k.startswith("stage")),
    "cold": sum(v.get("coldCompiles", 0) for v in d.values()),
    "aqeFeedback": len(aqe._FEEDBACK),
    "firstRowS": round(first if first is not None else -1.0, 4)}))
"""


def _run_child(script, cache_dir):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("SPARK_RAPIDS_TPU_CONF__SPARK__RAPIDS__TPU__SQL"
            "__ANALYSIS__LOCKDEP", None)
    proc = subprocess.run(
        [sys.executable, "-c", script, cache_dir, _SF],
        capture_output=True, text=True, timeout=600, cwd=ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_prewarm_serves_q6_with_zero_query_triggered_compiles(tmp_path):
    cache_dir = str(tmp_path / "compile_cache")
    a = _run_child(_CHILD_A, cache_dir)
    assert a["q6Rows"] > 0 and a["scanRows"] > 0
    assert a["cold"] > 0                  # the seeding run built for real
    assert a["stageFamilies"], a          # the scan planned a fused stage
    # ...and its signature landed in the prewarm corpus beside the index
    assert os.path.exists(os.path.join(cache_dir, "prewarm_corpus.jsonl"))
    b = _run_child(_CHILD_B, cache_dir)
    assert b["drained"], b
    assert b["failed"] == 0, b
    assert b["prewarmBuilt"] > 0, b       # bootstrap replayed the corpus
    assert b["q6Rows"] == a["q6Rows"]
    assert b["scanRows"] == a["scanRows"]
    # the acceptance invariant: the query thread triggered no stage
    # build (the prewarmed fused fn answered) and no cold compile of
    # ANY family (everything else classifies as a disk hit)
    assert b["stageCompiles"] == 0, b
    assert b["cold"] == 0, b
    assert b["firstRowS"] > 0, b
    # process A's cardinality-feedback bank rode the checkpoint beside
    # the signature index and reloaded at B's bootstrap (docs/aqe.md)
    assert b["aqeFeedback"] > 0, b
