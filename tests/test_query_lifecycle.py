"""Query-lifecycle observability (docs/observability.md §8): query-id
propagation, stage-boundary exchange statistics on all three shuffle
planes, estimate-vs-actual drift, the structured query log + report CLI,
the merged multi-worker timeline, the flight-dump query filter, and the
durable-tier GC budget.

Plus query lifecycle CONTROL (ISSUE 20, docs/service.md §4a): the
CancelToken state machine, deterministic mid-execution cancel and
suspend/resume via the chaos points, deadline enforcement at poll
boundaries, weighted-fair scheduling, and two-OS-process cancel
propagation over the shuffle META round trip.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.shuffle.exchange import (collect_stage_stats,
                                               compute_stage_stats)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _session(**conf):
    return TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE", **conf}).getOrCreate()


# ---------------------------------------------------------------------------
# Units: skew/p50 math, query-id minting, timeline merging
# ---------------------------------------------------------------------------

def test_stage_stats_skew_and_p50_units():
    """Exact unit semantics: p50 = median partition BYTES, skew = max
    partition bytes over MEAN partition bytes (1.0 = balanced)."""
    st = compute_stage_stats(3, "dcn", rows=[10, 20, 30, 40],
                             bytes_=[100, 200, 300, 600])
    assert st["partitions"] == 4
    assert st["totalRows"] == 100 and st["totalBytes"] == 1200
    assert st["p50Bytes"] == 250.0          # median of 100,200,300,600
    assert st["maxBytes"] == 600
    assert st["skew"] == 2.0                # 600 / mean(300)
    assert st["stageId"] == 3 and st["plane"] == "dcn"
    # degenerate shapes never divide by zero
    empty = compute_stage_stats(None, "ici", [], [])
    assert empty["skew"] == 1.0 and empty["p50Bytes"] == 0.0
    zeros = compute_stage_stats(1, "dcn", [0, 0], [0, 0])
    assert zeros["skew"] == 1.0


def test_query_id_minting_is_structural_and_monotonic():
    from spark_rapids_tpu.exec import query_context as qc

    class _N:
        def __init__(self, *children):
            self.children = list(children)

    plan = _N(_N(), _N(_N()))
    a = qc.mint_query_id(plan)
    b = qc.mint_query_id(plan)
    c = qc.mint_query_id(_N())
    # counter advances, structural digest is stable for the same shape
    assert a != b
    assert a.split("-")[1] == b.split("-")[1]
    assert a.split("-")[1] != c.split("-")[1]
    # the ambient scope: pool-style threads see the driving default
    ctx = qc.QueryContext("q-test")
    with qc.query_scope(ctx):
        assert qc.current_query_id() == "q-test"
        assert ctx.next_stage_id() == 1 and ctx.next_stage_id() == 2
        import threading
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(qc.current_query_id()))
        t.start()
        t.join()
        assert seen == ["q-test"]
    assert qc.current_query_id() is None


def test_merge_chrome_traces_filters_and_regroups():
    from spark_rapids_tpu.exec.tracing import merge_chrome_traces
    t0 = {"traceEvents": [
        {"ph": "X", "name": "a", "pid": 0, "tid": 1, "ts": 0, "dur": 5,
         "args": {"query": "q1"}},
        {"ph": "X", "name": "stale", "pid": 0, "tid": 1, "ts": 9,
         "dur": 1, "args": {"query": "q0"}},
        {"ph": "M", "name": "thread_name", "pid": 0, "tid": 1,
         "args": {"name": "tpu-task_0"}}]}
    t1 = {"traceEvents": [
        {"ph": "X", "name": "b", "pid": 0, "tid": 1, "ts": 2, "dur": 3,
         "args": {"query": "q1"}}]}
    merged = merge_chrome_traces([t0, t1], query_id="q1")
    xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"a", "b"}   # q0 filtered out
    assert {e["pid"] for e in xs} == {0, 1}        # per-source process
    assert all(e["args"]["query"] == "q1" for e in xs)
    assert merged["queryId"] == "q1" and merged["mergedSources"] == 2


# ---------------------------------------------------------------------------
# The q3-shaped acceptance query, on the local DCN and ICI planes
# ---------------------------------------------------------------------------

def _q3_tables(s):
    rng = np.random.default_rng(7)
    n = 8192
    line = pd.DataFrame({
        "l_order": rng.integers(0, 1000, n).astype("int64"),
        "l_price": rng.normal(100.0, 10.0, n)})
    orders = pd.DataFrame({
        "o_key": np.arange(1000, dtype="int64"),
        "o_cust": rng.integers(0, 100, 1000).astype("int64"),
        "o_date": rng.integers(0, 1000, 1000).astype("int64")})
    cust = pd.DataFrame({
        "c_key": np.arange(100, dtype="int64"),
        "c_seg": rng.integers(0, 3, 100).astype("int64")})
    s.createDataFrame(line).createOrReplaceTempView("p_lineitem")
    s.createDataFrame(orders).createOrReplaceTempView("p_orders")
    s.createDataFrame(cust).createOrReplaceTempView("p_customer")


_Q3 = ("SELECT l_price, o_date, c_seg FROM p_lineitem "
       "JOIN p_orders ON l_order = o_key "
       "JOIN p_customer ON o_cust = c_key "
       "WHERE o_date < 700 AND c_seg = 1")

_Q3_CONF = {
    "spark.rapids.tpu.sql.shuffle.partitions": "4",
    "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
}


def _run_q3(s):
    _q3_tables(s)
    rows = s.sql(_Q3).collect()
    assert len(rows) > 0
    return rows


def _assert_q3_observability(s, plane):
    """The ISSUE acceptance surface, shared by the DCN and ICI runs:
    EXPLAIN ANALYZE shows, per exchange node, partition count + p50/max
    partition bytes + skew factor, and per plan node est vs actual rows
    with a drift ratio; last_stage_stats carries the programmatic
    shape."""
    stats = s.last_stage_stats()
    assert len(stats) == 4, stats              # 2 per shuffled join
    for st in stats:
        assert st["plane"] == plane
        assert st["partitions"] == 4
        assert st["stageId"] is not None
        assert st["queryId"] == s.last_query_id()
        assert len(st["rows"]) == 4 and len(st["bytes"]) == 4
        assert st["totalRows"] == sum(st["rows"]) > 0
        assert st["skew"] >= 1.0 and st["p50Bytes"] >= 0
        assert st["maxBytes"] == max(st["bytes"])
    # stage ids number the boundaries 1..4 deterministically
    assert sorted(st["stageId"] for st in stats) == [1, 2, 3, 4]
    ea = s.explain_analyze()
    for needle in (f"exchange [{plane}]", "partitions=4", "p50Bytes=",
                   "maxBytes=", "skew=", "rows: est=", "drift=",
                   "queryId="):
        assert needle in ea, (needle, ea)
    drift = s.last_drift_report()
    assert drift and all(
        {"operator", "estRows", "actualRows", "ratio",
         "flagged"} <= set(d) for d in drift)
    return stats


def test_q3_dcn_stage_stats_and_drift_in_explain_analyze():
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "false",
                    **_Q3_CONF})
    _run_q3(s)
    _assert_q3_observability(s, "dcn")


def test_q3_ici_stage_stats_parity_with_dcn():
    """The ICI plane derives the SAME per-partition row statistics from
    its single counts readback as the DCN plane measures from staged
    slices — exchange-statistics parity across planes on the q3-shaped
    3-way join."""
    s_dcn = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "false",
                        **_Q3_CONF})
    _run_q3(s_dcn)
    dcn = {st["stageId"]: st["rows"]
           for st in _assert_q3_observability(s_dcn, "dcn")}
    s_ici = _session(**{
        "spark.rapids.tpu.sql.mesh.enabled": "true",
        "spark.rapids.tpu.sql.mesh.maxStageBytes": "1",
        "spark.rapids.tpu.sql.shuffle.plane": "ici",
        **_Q3_CONF})
    _run_q3(s_ici)
    ici = {st["stageId"]: st["rows"]
           for st in _assert_q3_observability(s_ici, "ici")}
    # identical hash partitioning => identical per-partition row vectors
    assert dcn == ici, (dcn, ici)


def test_stats_collection_overhead_within_coarse_factor():
    """Stage-stats collection rides the metrics gate; with metrics ON the
    exchange-heavy query stays within a coarse factor of metrics OFF
    (stats are derived once per exchange from already-host metadata —
    never per batch)."""
    from spark_rapids_tpu.api.functions import col

    def run(metrics_on):
        s = _session(**{
            "spark.rapids.tpu.sql.mesh.enabled": "false",
            "spark.rapids.tpu.sql.metrics.enabled":
                "true" if metrics_on else "false",
            "spark.rapids.tpu.sql.shuffle.partitions": "8"})
        rng = np.random.default_rng(3)
        df = pd.DataFrame({"k": rng.integers(0, 64, 20000).astype("int64"),
                           "v": rng.normal(0, 1, 20000)})
        frame = s.createDataFrame(df).repartition(8, col("k"))
        frame.collect()                      # warm compiles out of the timing
        t0 = time.perf_counter()
        for _ in range(3):
            frame.collect()
        return time.perf_counter() - t0, s

    off_s, s_off = run(False)
    assert not collect_stage_stats(s_off.last_plan()), \
        "metrics off must also gate stage stats"
    on_s, s_on = run(True)
    assert collect_stage_stats(s_on.last_plan())
    assert on_s < off_s * 5 + 1.0, (on_s, off_s)


def test_drift_threshold_flags_misestimates():
    """A filter whose selectivity is far from the 0.25 heuristic crosses
    the drift threshold and is flagged (report + EXPLAIN ANALYZE)."""
    from spark_rapids_tpu.api.functions import col
    s = _session(**{
        "spark.rapids.tpu.sql.observability.driftThreshold": "2.0",
        # keep the standalone filter visible as its own node
        "spark.rapids.tpu.sql.fusion.wholeStage": "false"})
    df = pd.DataFrame({"v": list(range(10000))})
    got = s.createDataFrame(df).filter(col("v") < 10).collect()
    assert len(got) == 10
    drift = s.last_drift_report()
    flagged = [d for d in drift if d["flagged"]]
    # est = 10000 * 0.25 = 2500 vs actual 10 -> ratio 0.004, flagged
    f = [d for d in flagged if d["operator"] == "TpuFilterExec"]
    assert f and f[0]["estRows"] == 2500 and f[0]["actualRows"] == 10, \
        drift
    assert "! drift" in s.explain_analyze()
    # widen the threshold past the miss: the same query stops flagging
    s2 = _session(**{
        "spark.rapids.tpu.sql.observability.driftThreshold": "100000",
        "spark.rapids.tpu.sql.fusion.wholeStage": "false"})
    s2.createDataFrame(df).filter(col("v") < 10).collect()
    assert not [d for d in s2.last_drift_report() if d["flagged"]]


def test_drift_perfectly_estimated_empty_node_not_flagged():
    """est=0 / actual=0 is a PERFECT estimate (ratio 1.0), never the
    report's worst misestimate."""
    from spark_rapids_tpu.plan import estimates

    class _M(dict):
        def get(self, k, d=None):
            return dict.get(self, k, d)

    class _N:
        def __init__(self):
            self.children = []
            self.metrics = _M()

    n = _N()
    n.est_rows = 0
    n.metrics["numOutputRows"] = 0
    rep = estimates.drift_report(n)
    assert rep and rep[0]["ratio"] == 1.0 and not rep[0]["flagged"], rep


def test_pool_threads_attribute_to_their_own_concurrent_query():
    """Two CONCURRENT queries in one process: each query's task-pool
    events attribute to its OWN query id (run_partition_tasks routes the
    submitting thread's context explicitly), never to whichever query
    entered the process default last."""
    import threading
    from spark_rapids_tpu.exec import query_context as qc
    from spark_rapids_tpu.exec.tasks import run_partition_tasks
    barrier = threading.Barrier(2, timeout=30)
    got = {}

    def run(qname):
        with qc.query_scope(qc.QueryContext(qname)):
            barrier.wait()       # both defaults pushed before any task

            def task(pid, part):
                barrier.wait()   # tasks of both queries in flight
                return qc.current_query_id()

            got[qname] = set(run_partition_tasks([0, 1], task,
                                                 max_workers=2))

    threads = [threading.Thread(target=run, args=(q,))
               for q in ("q-one", "q-two")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert got == {"q-one": {"q-one"}, "q-two": {"q-two"}}, got


# ---------------------------------------------------------------------------
# Query log + report CLI
# ---------------------------------------------------------------------------

def test_query_log_record_and_report_cli(tmp_path):
    log_dir = str(tmp_path / "qlog")
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "false",
                    "spark.rapids.tpu.sql.telemetry.queryLog.dir": log_dir,
                    **_Q3_CONF})
    _run_q3(s)
    path = os.path.join(log_dir, f"query_log-{os.getpid()}.jsonl")
    assert os.path.exists(path)
    rec = [json.loads(line) for line in open(path)][-1]
    from spark_rapids_tpu.service.query_log import QUERY_LOG_FIELDS
    assert set(rec) <= set(QUERY_LOG_FIELDS)
    assert rec["queryId"] == s.last_query_id()
    assert rec["planCache"] in ("hit", "miss", "uncacheable", "off")
    assert rec["resultCache"] in ("hit", "miss", "uncacheable", "off")
    assert len(rec["stageStats"]) == 4
    assert rec["stageRetries"] == 0 and rec["faultsFired"] == 0
    assert rec["wallS"] > 0 and rec["operators"]
    assert rec["drift"]["nodes"] > 0
    # the CLI renders a digest naming the query, skew and drift
    from tools.query_report import render
    text = render([path])
    assert rec["queryId"] in text
    assert "skewest exchange" in text
    assert "top operators by time" in text
    # and survives being driven as a subprocess CLI
    out = subprocess.run(
        [sys.executable, "-m", "tools.query_report", path],
        capture_output=True, text=True, cwd=_REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0 and rec["queryId"] in out.stdout


# ---------------------------------------------------------------------------
# Flight-recorder query scoping
# ---------------------------------------------------------------------------

def test_flight_dump_filters_by_query_id(tmp_path):
    from spark_rapids_tpu.exec import query_context as qc
    from spark_rapids_tpu.service import telemetry as tel
    tel.FlightRecorder.reset()
    try:
        with qc.query_scope(qc.QueryContext("qAAA")):
            tel.flight_record("span", "a-span", {"durS": 1})
        with qc.query_scope(qc.QueryContext("qBBB")):
            tel.flight_record("span", "b-span", {"durS": 1})
        tel.flight_record("conf", "ambient-key", {"value": "1"})
        # events carry the ambient query id
        evs = {e["name"]: e for e in tel.FlightRecorder.get().events()}
        assert evs["a-span"]["data"]["query"] == "qAAA"
        assert evs["b-span"]["data"]["query"] == "qBBB"
        assert "query" not in evs["ambient-key"].get("data", {})
        # a query-scoped dump names the query and filters the other one
        path = tel.FlightRecorder.get().dump(
            path=str(tmp_path / "flight-qAAA.json"), query_id="qAAA")
        doc = json.load(open(path))
        names = [e["name"] for e in doc["events"]]
        assert "a-span" in names and "ambient-key" in names
        assert "b-span" not in names
        assert doc["queryId"] == "qAAA"
        # the default filename carries the failing query id
        auto = tel.FlightRecorder.get().dump(query_id="qAAA")
        try:
            assert "qAAA" in os.path.basename(auto)
        finally:
            os.unlink(auto)
    finally:
        tel.FlightRecorder.reset()


# ---------------------------------------------------------------------------
# Durable shuffle tier GC budget
# ---------------------------------------------------------------------------

def test_durable_gc_budget_evicts_oldest_completed(tmp_path):
    import glob
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.service.telemetry import MetricsRegistry
    from spark_rapids_tpu.shuffle.transport import ShuffleStore
    d = str(tmp_path / "durable")
    batch = ColumnarBatch.from_pydict(
        {"a": list(range(1000))}).fetch_to_host()
    nbytes = sum(int(a.nbytes) for c in batch.columns
                 for a in c.arrays())
    # budget fits ~2 shuffles; the third completion evicts the oldest
    store = ShuffleStore(durable_dir=d, durable_budget=2 * nbytes + 64)
    before = MetricsRegistry.get().counter(
        "tpu_durable_evicted_bytes_total").value
    for sid in (1, 2, 3):
        store.register_batch(sid, 0, batch)
        store.mark_complete(sid)
    assert not glob.glob(os.path.join(d, "buf-1-*")), \
        "oldest completed shuffle's durable files must evict"
    assert not os.path.exists(os.path.join(d, "complete-1"))
    assert glob.glob(os.path.join(d, "buf-3-*")), \
        "the newest completed shuffle is never evicted"
    assert MetricsRegistry.get().counter(
        "tpu_durable_evicted_bytes_total").value >= before + nbytes
    # eviction touches only the durable tier: in-memory still serves
    assert store.local_batches(1, 0)
    # a reloading store obeys the same budget
    store2 = ShuffleStore(durable_dir=d, durable_budget=nbytes + 64)
    n = store2.reload_durable()
    assert n >= 1
    assert glob.glob(os.path.join(d, "buf-3-*"))
    assert not glob.glob(os.path.join(d, "buf-2-*"))
    # budget off (0) never evicts
    d2 = str(tmp_path / "durable2")
    store3 = ShuffleStore(durable_dir=d2, durable_budget=0)
    for sid in (1, 2, 3):
        store3.register_batch(sid, 0, batch)
        store3.mark_complete(sid)
    assert len(glob.glob(os.path.join(d2, "buf-*-*.npz"))) == 3


# ---------------------------------------------------------------------------
# Two-OS-process acceptance: one merged timeline, one query id, logs
# ---------------------------------------------------------------------------

_WORKER = """
import sys, json, os
sys.path.insert(0, {repo!r})
os.environ.setdefault("SPARK_RAPIDS_TPU_COMPILE_CACHE", "off")
from spark_rapids_tpu.shuffle.manager import init_worker

wid = int(sys.argv[1]); n = int(sys.argv[2]); log_dir = sys.argv[3]
ctx = init_worker(wid, n)
print(json.dumps({{"port": ctx.port}}), flush=True)
peers = json.loads(sys.stdin.readline())
ctx.set_peers({{int(k): tuple(v) for k, v in peers.items()}})

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col

s = TpuSession.builder.config({{
    "spark.rapids.tpu.sql.explain": "NONE",
    "spark.rapids.tpu.sql.shuffle.partitions": "4",
    "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
    "spark.rapids.tpu.sql.tracing.timeline": "true",
    "spark.rapids.tpu.sql.telemetry.queryLog.dir": log_dir,
}}).getOrCreate()

base = wid * 1000
ks = [(base + i) % 7 for i in range(200)]
vs = [float(i % 13) for i in range(200)]
s.createDataFrame({{"k": ks, "v": vs}}).createOrReplaceTempView("t")
rk = list(range(7))
s.createDataFrame({{"k": rk, "w": [k * 10.0 for k in rk]}}) \\
    .createOrReplaceTempView("dim")

out = (s.table("t")
       .join(s.table("dim"), on="k", how="inner")
       .groupBy("k")
       .agg(F.sum(col("v") + col("w")).alias("sv"))
       .collect())

rec = getattr(s, "_last_span_recorder")
log_path = os.path.join(log_dir, f"query_log-{{os.getpid()}}.jsonl")
print(json.dumps({{
    "rows": [list(r) for r in out],
    "qid": s.last_query_id(),
    "stats": s.last_stage_stats(),
    "trace": rec.chrome_trace(),
    "ea": s.explain_analyze(),
    "log": [json.loads(l) for l in open(log_path)],
}}), flush=True)
ctx.shutdown()
"""


def test_two_process_merged_timeline_and_query_log(tmp_path):
    """ISSUE 14 acceptance: a two-OS-process distributed query produces
    ONE merged timeline whose spans from BOTH workers carry the same
    query id; each worker's query-log record carries stage stats,
    retries and cache verdicts; the distributed exchange statistics
    (summed across workers) agree with the same query's local-mode
    statistics; and EXPLAIN ANALYZE on the distributed plane shows the
    exchange stats + drift surface too."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    procs = []
    for wid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER.format(repo=_REPO),
             str(wid), "2", str(tmp_path / f"qlog-{wid}")],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=env, text=True))
    results = []
    try:
        ports = {}
        for wid, p in enumerate(procs):
            line = p.stdout.readline()
            assert line, p.stderr.read()
            ports[wid] = ("127.0.0.1", json.loads(line)["port"])
        peers = json.dumps({str(w): list(a) for w, a in ports.items()})
        for p in procs:
            p.stdin.write(peers + "\n")
            p.stdin.flush()
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err
            for line in out.splitlines():
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "qid" in d:
                    results.append(d)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert len(results) == 2
    w0, w1 = results

    # --- the lockstep query id is SHARED across both OS processes
    qid = w0["qid"]
    assert qid and w1["qid"] == qid

    # --- one merged timeline, spans from BOTH workers, one query id
    from spark_rapids_tpu.exec.tracing import merge_chrome_traces
    merged = merge_chrome_traces([w0["trace"], w1["trace"]],
                                 query_id=qid)
    spans = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert spans
    pids = {e["pid"] for e in spans}
    assert pids == {0, 1}, pids
    assert all(e["args"]["query"] == qid for e in spans)
    assert merged["queryId"] == qid

    # --- each worker's query-log record: stage stats, retries, verdicts
    for w in (w0, w1):
        rec = w["log"][-1]
        assert rec["queryId"] == qid
        assert rec["stageStats"] and all(
            st["plane"] == "dcn" for st in rec["stageStats"])
        assert "stageRetries" in rec and rec["stageRetries"] == 0
        assert rec["planCache"] in ("hit", "miss", "uncacheable", "off")
        assert rec["resultCache"] in ("hit", "miss", "uncacheable",
                                      "off")

    # --- EXPLAIN ANALYZE shows the exchange stats + drift surface on
    # the distributed plane as well
    for w in (w0, w1):
        for needle in ("exchange [dcn]", "p50Bytes=", "skew=",
                       "rows: est=", f"queryId={qid}"):
            assert needle in w["ea"], (needle, w["ea"][:2000])

    # --- exchange-statistics parity: distributed per-partition rows
    # summed across workers == the SAME query's local-mode statistics
    # (identical hash partitioning; the dim table is replicated on both
    # workers so its exchange doubles — compare the fact-side exchange)
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "false",
                    **_Q3_CONF})
    frames = []
    for wid in range(2):
        base = wid * 1000
        frames.append(pd.DataFrame({
            "k": [(base + i) % 7 for i in range(200)],
            "v": [float(i % 13) for i in range(200)]}))
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    s.createDataFrame(pd.concat(frames)).createOrReplaceTempView("t")
    s.createDataFrame({"k": list(range(7)),
                       "w": [k * 10.0 for k in range(7)]}) \
        .createOrReplaceTempView("dim")
    (s.table("t").join(s.table("dim"), on="k", how="inner")
     .groupBy("k").agg(F.sum(col("v") + col("w")).alias("sv")).collect())
    local = {st["stageId"]: st for st in s.last_stage_stats()}
    d0 = {st["stageId"]: st for st in w0["stats"]}
    d1 = {st["stageId"]: st for st in w1["stats"]}
    assert set(local) == set(d0) == set(d1), (local.keys(), d0.keys())
    fact_sids = [sid for sid, st in local.items()
                 if st["totalRows"] == 400]
    assert fact_sids, local
    for sid in fact_sids:
        summed = [a + b for a, b in zip(d0[sid]["rows"],
                                        d1[sid]["rows"])]
        assert summed == local[sid]["rows"], (sid, summed,
                                              local[sid]["rows"])


# ---------------------------------------------------------------------------
# Query lifecycle control (ISSUE 20): cancel, suspend/resume, preemption
# ---------------------------------------------------------------------------

def test_cancel_token_state_machine():
    """Unit transitions of the CancelToken: idempotent cancel with
    first-reason-wins, suspend/resume re-arming, check() raising, and
    the append-only transition log."""
    import pytest
    from spark_rapids_tpu.exec import lifecycle as lc

    tok = lc.CancelToken("q-unit")
    assert tok.state == lc.RUNNING
    assert not tok.cancelled and not tok.suspend_requested
    tok.check()                                    # clean: no raise

    assert tok.request_suspend("preempt") is True
    assert tok.request_suspend("again") is False   # already requested
    with pytest.raises(lc.QuerySuspendedError):
        tok.check()
    tok.park_cursor(stage="stage-1", partitions_done=[0, 2])
    tok.mark_suspended()
    assert tok.state == lc.SUSPENDED
    assert tok.cursor == {"stage": "stage-1", "partitionsDone": [0, 2]}

    tok.resume()
    assert tok.state == lc.RESUMED and not tok.suspend_requested
    tok.check()                                    # resumed: clean again

    assert tok.cancel("user-request") is True
    assert tok.cancel("too-late") is False         # idempotent
    with pytest.raises(lc.QueryCancelledError) as ei:
        tok.check()
    assert ei.value.reason == "user-request"       # first reason wins
    assert tok.request_suspend() is False          # cancelled is terminal

    assert [t["state"] for t in tok.transitions] == [
        lc.RUNNING, lc.SUSPEND_REQUESTED, lc.SUSPENDED, lc.RESUMED,
        lc.CANCELLED]


def test_cancel_inject_fails_query_and_plan_cache_survives():
    """A deterministic mid-execution cancel (chaos point cancel.inject)
    raises the typed QueryCancelledError, unregisters the query, records
    the transition for post-mortems — and the plan cache still serves
    the identical query correctly afterwards. Runs under
    bufferLedger=enforce, so a leaked buffer on the cancel unwind path
    would raise instead of passing."""
    import pytest
    from spark_rapids_tpu.analysis import faults
    from spark_rapids_tpu.exec import lifecycle as lc

    s = _session(**{
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
        "spark.rapids.tpu.sql.analysis.bufferLedger": "enforce"})
    ks = [i % 7 for i in range(300)]
    vs = [float(i % 13) for i in range(300)]
    s.createDataFrame({"k": ks, "v": vs}).createOrReplaceTempView("lct")
    sql = "SELECT k, sum(v) AS sv FROM lct GROUP BY k ORDER BY k"
    oracle = s.sql(sql).collect()

    faults.install("cancel.inject")
    try:
        with pytest.raises(lc.QueryCancelledError) as ei:
            s.sql(sql).collect()
    finally:
        faults.reset()
    assert ei.value.reason == "cancel.inject"
    qid = ei.value.query_id
    assert qid and qid not in lc.live_queries()    # unregistered
    states = [t["state"] for t in lc.transitions_for(qid)]
    assert lc.CANCELLED in states                  # retired log kept

    # the plan is not poisoned: the same text serves again, correctly
    assert s.sql(sql).collect() == oracle


def test_deadline_lapse_cancels_mid_execution():
    """Satellite 1: a lapsed deadline now fires DURING execution through
    the cooperative poll (reason "deadline"), not only while queued."""
    import time as _time

    import pytest
    from spark_rapids_tpu.exec import lifecycle as lc
    from spark_rapids_tpu.exec import query_context as qc

    s = _session(**{"spark.rapids.tpu.sql.shuffle.partitions": "4"})
    ks = [i % 5 for i in range(200)]
    s.createDataFrame({"k": ks, "v": [float(i) for i in range(200)]}) \
        .createOrReplaceTempView("ddt")
    with qc.deadline_scope(_time.perf_counter() - 0.001):   # lapsed
        with pytest.raises(lc.QueryCancelledError) as ei:
            s.sql("SELECT k, sum(v) AS sv FROM ddt GROUP BY k").collect()
    assert ei.value.reason == "deadline"


def test_preempt_inject_parks_and_resume_is_oracle_identical():
    """Satellite 4 core: a deterministic suspension (preempt.inject)
    mid-execution parks the ticket WITHOUT failing it; the service
    counts the preemption, resume() re-admits it through the scheduler,
    and the result is oracle-identical — under bufferLedger=enforce +
    lockdep=enforce."""
    import time as _time

    from spark_rapids_tpu.analysis import faults
    from spark_rapids_tpu.service.server import QueryService, TenantSpec

    s = _session(**{
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
        "spark.rapids.tpu.sql.analysis.lockdep": "enforce",
        "spark.rapids.tpu.sql.analysis.bufferLedger": "enforce"})
    ks = [i % 7 for i in range(300)]
    vs = [float(i % 11) for i in range(300)]
    s.createDataFrame({"k": ks, "v": vs}).createOrReplaceTempView("ppt")
    sql = "SELECT k, sum(v) AS sv FROM ppt GROUP BY k ORDER BY k"
    oracle = s.sql(sql).collect()

    svc = QueryService(s, max_workers=1,
                       tenants=[TenantSpec("t", priority=1)])
    faults.install("preempt.inject")
    try:
        ticket = svc.submit("t", sql, label="preempt-me")
        deadline = _time.time() + 20
        while _time.time() < deadline and not svc.suspended_queries():
            _time.sleep(0.01)
        parked = svc.suspended_queries()
        assert parked, "query never parked on the injected suspension"
        assert svc.stats()["tenants"]["t"]["preempted"] == 1
        assert not ticket.done()

        resumed = svc.resume(parked[0])
        assert resumed is ticket
        rows = ticket.result(timeout=120).rows()
        assert rows == oracle
        st = svc.stats()["tenants"]["t"]
        assert st["resumed"] == 1 and st["completed"] == 1
        assert svc.suspended_queries() == []
    finally:
        faults.reset()
        svc.close()


def test_wfq_weighted_share_and_no_starvation():
    """Weighted-fair scheduling: with equal priorities and one worker
    slot, a weight-4 tenant is served ~4x as often as a weight-1 tenant
    early on, and the light tenant is never starved."""
    import threading
    import time as _time

    from spark_rapids_tpu.service.server import QueryService, TenantSpec

    s = _session(**{
        "spark.rapids.tpu.sql.service.scheduler.policy": "wfq"})
    svc = QueryService(s, max_workers=1, tenants=[
        TenantSpec("blk", priority=0, slots=1),
        TenantSpec("a", priority=0, slots=1, weight=4.0),
        TenantSpec("b", priority=0, slots=1, weight=1.0)])
    order = []
    mu = threading.Lock()
    gate = threading.Event()

    def mk(name):
        def run():
            with mu:
                order.append(name)
            return name
        return run

    try:
        blocker = svc.submit("blk", lambda: gate.wait(30))
        deadline = _time.time() + 10
        while _time.time() < deadline and svc.stats()["running"] < 1:
            _time.sleep(0.005)
        tickets = []
        for _ in range(5):               # interleaved arrivals
            tickets.append(svc.submit("a", mk("a")))
            tickets.append(svc.submit("b", mk("b")))
        gate.set()
        for t in tickets:
            t.result(timeout=60)
        blocker.result(timeout=60)
        # weight 4 vs 1: the heavy tenant dominates the early pops...
        assert order[:6].count("a") >= 4, order
        # ...but the light tenant still gets its full share served
        assert order.count("a") == 5 and order.count("b") == 5, order
        stats = svc.stats()
        assert stats["policy"] == "wfq"
        # normalized service: a's 5 pops at cost/4 vs b's 5 at cost/1
        assert stats["tenants"]["a"]["serviceUnits"] < \
            stats["tenants"]["b"]["serviceUnits"]
    finally:
        gate.set()
        svc.close()


_CANCEL_WORKER = """
import sys, json, os
sys.path.insert(0, {repo!r})
os.environ.setdefault("SPARK_RAPIDS_TPU_COMPILE_CACHE", "off")
from spark_rapids_tpu.shuffle.manager import init_worker

wid = int(sys.argv[1]); n = int(sys.argv[2])
ctx = init_worker(wid, n)
print(json.dumps({{"port": ctx.port}}), flush=True)
peers = json.loads(sys.stdin.readline())
ctx.set_peers({{int(k): tuple(v) for k, v in peers.items()}})

from spark_rapids_tpu.api.session import TpuSession

s = TpuSession.builder.config({{
    "spark.rapids.tpu.sql.explain": "NONE",
    "spark.rapids.tpu.sql.shuffle.partitions": "4",
    "spark.rapids.tpu.sql.recovery.retryBackoff": "0.0",
}}).getOrCreate()

base = wid * 1000
ks = [(base + i) % 7 for i in range(200)]
vs = [float(i % 13) for i in range(200)]
s.createDataFrame({{"k": ks, "v": vs}}).createOrReplaceTempView("t")

if wid == 0:
    # worker 0's query cancels at its FIRST poll; worker 1 only learns
    # about it from the cancelled stamp on worker 0's META reply
    from spark_rapids_tpu.analysis import faults
    faults.install("cancel.inject")

err = None
try:
    s.sql("SELECT k, sum(v) AS sv FROM t GROUP BY k").collect()
except Exception as e:
    err = [type(e).__name__, str(e)]

from spark_rapids_tpu.exec.spill import BufferCatalog
cat = BufferCatalog.peek()
dev = sum((cat.tenant_device_bytes() or {{}}).values()) if cat else 0
print(json.dumps({{"err": err, "tenantDeviceBytes": dev}}), flush=True)
sys.stdin.readline()     # stay alive to serve the peer's META polls
ctx.shutdown()
"""


def test_two_process_cancel_propagates_over_meta(tmp_path):
    """Distributed cancellation: worker 0 cancels locally
    (cancel.inject); worker 1, blocked fetching worker 0's outputs,
    sees the cancelled stamp on the META reply and cancels its OWN
    token — both workers fail with the typed QueryCancelledError, no
    fetch-timeout wedge, and tenant device bytes are zero on both."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    procs = []
    for wid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CANCEL_WORKER.format(repo=_REPO),
             str(wid), "2"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=env, text=True))
    results = {}
    try:
        ports = {}
        for wid, p in enumerate(procs):
            line = p.stdout.readline()
            assert line, p.stderr.read()
            ports[wid] = ("127.0.0.1", json.loads(line)["port"])
        peers = json.dumps({str(w): list(a) for w, a in ports.items()})
        for p in procs:
            p.stdin.write(peers + "\n")
            p.stdin.flush()
        for wid, p in enumerate(procs):
            line = p.stdout.readline()
            assert line, p.stderr.read()
            results[wid] = json.loads(line)
        for p in procs:            # release the stay-alive gate
            p.stdin.write("done\n")
            p.stdin.flush()
        for p in procs:
            p.communicate(timeout=60)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    assert results[0]["err"] is not None, results
    assert results[0]["err"][0] == "QueryCancelledError", results[0]
    assert "cancel.inject" in results[0]["err"][1]
    assert results[1]["err"] is not None, results
    assert results[1]["err"][0] == "QueryCancelledError", results[1]
    assert "peer-cancelled" in results[1]["err"][1], results[1]
    for wid in (0, 1):
        assert results[wid]["tenantDeviceBytes"] == 0


def test_query_log_records_lifecycle_transitions(tmp_path):
    """Satellite 5: a suspended-then-resumed query's log record carries
    the full transition list in the ``lifecycle`` field; a plain query's
    record omits the field entirely."""
    import time as _time

    from spark_rapids_tpu.analysis import faults
    from spark_rapids_tpu.service.server import QueryService, TenantSpec

    log_dir = str(tmp_path / "qlog")
    s = _session(**{
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
        "spark.rapids.tpu.sql.telemetry.queryLog.dir": log_dir})
    s.createDataFrame({"k": [i % 3 for i in range(60)],
                       "v": [float(i) for i in range(60)]}) \
        .createOrReplaceTempView("qlt")
    sql = "SELECT k, sum(v) AS sv FROM qlt GROUP BY k ORDER BY k"
    s.sql(sql).collect()                       # plain: no lifecycle field

    svc = QueryService(s, max_workers=1,
                       tenants=[TenantSpec("t", priority=1)])
    faults.install("preempt.inject")
    try:
        ticket = svc.submit("t", sql)
        deadline = _time.time() + 20
        while _time.time() < deadline and not svc.suspended_queries():
            _time.sleep(0.01)
        assert svc.suspended_queries()
        svc.resume(svc.suspended_queries()[0])
        ticket.result(timeout=120)
    finally:
        faults.reset()
        svc.close()

    recs = []
    for name in os.listdir(log_dir):
        with open(os.path.join(log_dir, name)) as f:
            recs.extend(json.loads(l) for l in f if l.strip())
    cycled = [r for r in recs if r.get("lifecycle")]
    assert cycled, recs
    states = [t["state"] for t in cycled[0]["lifecycle"]]
    assert states[0] == "running"
    assert "suspended" in states and "resumed" in states
    plain = [r for r in recs if not r.get("lifecycle")]
    assert plain                               # the direct collect
