"""Fault-tolerant execution (ISSUE 13): the recoverable-error taxonomy
maps every failure to the right action, the stage-retry driver absorbs
recoverable failures within its conf budget, shuffle outputs survive in
the durable tier, workers die and rejoin, and the deterministic
fault-injection harness (analysis/faults.py) makes all of it reachable
from tests — chaos runs return results identical to fault-free runs,
with the recovery trail visible in telemetry and the flight record
(docs/resilience.md).
"""

import os
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.analysis import faults
from spark_rapids_tpu.analysis.faults import FaultSpecError
from spark_rapids_tpu.api.session import RuntimeConf, TpuSession
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec import recovery
from spark_rapids_tpu.exec.recovery import (InjectedTaskFault,
                                            RecoveryAction, StageRetryState,
                                            classify, retry_stage)
from spark_rapids_tpu.exec.spill import BufferLostError
from spark_rapids_tpu.service.telemetry import FlightRecorder, MetricsRegistry
from spark_rapids_tpu.shuffle.manager import WorkerContext
from spark_rapids_tpu.shuffle.transport import (ShuffleClient,
                                                ShuffleDesyncError,
                                                ShuffleFetchError,
                                                ShuffleProtocolError,
                                                ShuffleStore,
                                                ShuffleWorkerLostError)


@pytest.fixture(autouse=True)
def _clean_harness():
    """Every test leaves the process-global chaos plan disarmed and the
    mesh re-admitted (both are module singletons by design)."""
    yield
    faults.reset()
    recovery.clear_mesh_lost()
    recovery.reset_cache()


def _session(**conf):
    return TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE", **conf}).getOrCreate()


def _counter(name: str) -> float:
    return float(MetricsRegistry.get().counter(name, "x").value)


def _flight_names(kind: str):
    return [e["name"] for e in FlightRecorder.get().events()
            if e["kind"] == kind]


# ---------------------------------------------------------------------------
# Taxonomy: every failure class maps to the right recovery action
# ---------------------------------------------------------------------------

def test_classify_maps_each_taxonomy_type():
    assert classify(ShuffleDesyncError("x")) is RecoveryAction.FAIL_QUERY
    assert classify(ShuffleProtocolError("x")) is RecoveryAction.FAIL_QUERY
    assert classify(ShuffleWorkerLostError(3, "w3 died")) is \
        RecoveryAction.RETRY_STAGE
    assert classify(ShuffleFetchError("gave up")) is \
        RecoveryAction.RETRY_STAGE
    assert classify(BufferLostError("b9")) is RecoveryAction.RETRY_STAGE
    assert classify(InjectedTaskFault("poison")) is \
        RecoveryAction.RETRY_STAGE
    assert classify(ConnectionError("reset")) is RecoveryAction.RETRY_FETCH
    assert classify(OSError("io")) is RecoveryAction.RETRY_FETCH
    # unknown failures propagate unmasked — recovery never eats a bug
    assert classify(ValueError("bug")) is RecoveryAction.FAIL_QUERY


def test_stage_retry_budget_and_backoff():
    rs = StageRetryState("t", max_retries=2, backoff_s=0.0)
    rs.failed(ShuffleFetchError("a"))          # attempt 1: absorbed
    rs.failed(ShuffleFetchError("b"))          # attempt 2: absorbed
    with pytest.raises(ShuffleFetchError, match="c"):
        rs.failed(ShuffleFetchError("c"))      # budget exhausted
    assert rs.attempts == 3


def test_stage_retry_fail_query_types_propagate_immediately():
    rs = StageRetryState("t", max_retries=5, backoff_s=0.0)
    with pytest.raises(ShuffleDesyncError):
        rs.failed(ShuffleDesyncError("diverged"))
    with pytest.raises(ValueError):
        rs.failed(ValueError("not ours"))
    assert rs.attempts == 0                    # never counted as retries


def test_stage_retry_caller_gate_blocks():
    rs = StageRetryState("t", retryable=lambda e: False,
                         max_retries=5, backoff_s=0.0)
    with pytest.raises(ShuffleFetchError):
        rs.failed(ShuffleFetchError("indeterminate upstream"))


def test_retry_stage_driver_recovers_and_discards_partial_state():
    calls = {"n": 0, "discards": []}

    def attempt():
        calls["n"] += 1
        if calls["n"] < 3:
            raise InjectedTaskFault(f"poison {calls['n']}")
        return "ok"

    def on_retry(exc, attempt_no):
        calls["discards"].append(attempt_no)

    before = _counter("tpu_stage_retries_total")
    out = retry_stage("unit", attempt, on_retry=on_retry,
                      max_retries=5, backoff_s=0.0)
    assert out == "ok" and calls["n"] == 3
    assert calls["discards"] == [1, 2]
    assert _counter("tpu_stage_retries_total") >= before + 2
    assert any("stage-retry-unit" in n for n in _flight_names("recovery"))
    assert any("recovered-unit" in n for n in _flight_names("recovery"))


def test_recovery_knobs_prime_from_session_conf():
    _session(**{"spark.rapids.tpu.sql.recovery.maxStageRetries": "7",
                "spark.rapids.tpu.sql.recovery.retryBackoff": "0.0",
                "spark.rapids.tpu.sql.shuffle.durable": "true"})
    assert recovery.max_stage_retries() == 7
    assert recovery.retry_backoff_s() == 0.0
    assert recovery.shuffle_durable()
    # a runtime conf change re-primes (the audit-cache discipline)
    s = TpuSession.active()
    RuntimeConf(s).set("spark.rapids.tpu.sql.recovery.maxStageRetries", "3")
    assert recovery.max_stage_retries() == 3


# ---------------------------------------------------------------------------
# Fault harness: spec grammar, deterministic firing, callbacks
# ---------------------------------------------------------------------------

def test_fault_spec_grammar():
    plan = faults.parse_spec(
        "fetch.fail:2;task.poison@p1b3;conn.kill@4;worker.die;mesh.drop")
    assert [f.point for f in plan] == ["fetch.fail", "task.poison",
                                      "conn.kill", "worker.die",
                                      "mesh.drop"]
    assert plan[0].remaining == 2
    assert (plan[1].pid, plan[1].batch) == (1, 3)
    assert plan[2].after == 4
    assert faults.parse_spec("") == []
    for bad in ("nope.fault", "fetch.fail:0", "fetch.fail:x",
                "task.poison@z9", "worker.die@p1", "fetch.fail@@"):
        with pytest.raises(FaultSpecError):
            faults.parse_spec(bad)


def test_fault_firing_counts_and_selectors():
    faults.install("task.poison:2@p1")
    assert faults.armed()
    assert not faults.fire("task.poison", pid=0)     # selector mismatch
    assert faults.fire("task.poison", pid=1)
    assert faults.fire("task.poison", pid=1)
    assert not faults.fire("task.poison", pid=1)     # count exhausted
    assert not faults.armed()
    # conn.kill fires only once >= `after` chunks were sent
    faults.install("conn.kill@3")
    assert not faults.fire("conn.kill", chunk=2)
    assert faults.fire("conn.kill", chunk=3)


def test_fault_firing_is_observable_and_callbacks_run():
    fired = []
    before = _counter("tpu_faults_injected_total")
    faults.install("worker.die")
    faults.on_fire("worker.die", lambda: fired.append(1))
    faults.on_fire("worker.die", lambda: 1 / 0)   # broken hooks swallowed
    assert faults.fire("worker.die")
    assert fired == [1]
    assert faults.fired_total() == 1
    assert _counter("tpu_faults_injected_total") == before + 1
    assert "worker.die" in _flight_names("fault")


def test_fault_spec_primes_from_session_conf():
    _session(**{"spark.rapids.tpu.sql.faults.spec": "fetch.fail:3"})
    assert faults.armed()
    s = TpuSession.active()
    RuntimeConf(s).set("spark.rapids.tpu.sql.faults.spec", "")
    assert not faults.armed()


# ---------------------------------------------------------------------------
# Durable shuffle tier
# ---------------------------------------------------------------------------

def _host_batch(vals):
    return ColumnarBatch.from_pydict({"a": list(vals)}).fetch_to_host()


def test_durable_store_persists_and_reloads(tmp_path):
    d = str(tmp_path / "w0")
    store = ShuffleStore(durable_dir=d)
    store.register_batch(4, 0, _host_batch([1, 2, 3]))
    store.register_batch(4, 1, _host_batch([4, 5]))
    store.mark_complete(4)
    assert len([f for f in os.listdir(d) if f.endswith(".npz")]) == 2
    # a rejoining worker (fresh process analog): new store, same dir
    store2 = ShuffleStore(durable_dir=d)
    assert store2.reload_durable() == 2
    assert store2.is_complete(4)
    metas = store2.metas(4, [0, 1])
    assert sorted(m.reduce_id for m in metas) == [0, 1]
    got = store2.payload(metas[0].buffer_id)
    assert got is not None
    # removal unlinks the durable files (no leak across shuffles)
    store2.remove_shuffle(4)
    assert not [f for f in os.listdir(d) if f.startswith("buf-4-")]
    assert ShuffleStore(durable_dir=d).reload_durable() == 0


def test_durable_store_tolerates_torn_write(tmp_path):
    d = str(tmp_path / "w0")
    store = ShuffleStore(durable_dir=d)
    store.register_batch(5, 0, _host_batch([1]))
    # a death mid-write leaves a json without a readable npz
    stem = os.path.join(d, "buf-5-1-999")
    with open(stem + ".json", "w") as f:
        f.write('{"buffer_id": 999')        # torn
    with open(stem + ".npz", "wb") as f:
        f.write(b"not-an-npz")
    store2 = ShuffleStore(durable_dir=d)
    assert store2.reload_durable() == 1     # the intact buffer only


def test_local_durable_read_keeps_slices_and_pins_to_disk(tmp_path):
    from spark_rapids_tpu.exec.spill import (SpillableColumnarBatch,
                                             StorageTier)
    from spark_rapids_tpu.shuffle.exchange import (LocalShuffle,
                                                   OUTPUT_FOR_SHUFFLE_PRIORITY)
    _session(**{"spark.rapids.tpu.memory.spillDir": str(tmp_path)})
    sh = LocalShuffle(2, durable=True)
    for p, vals in ((0, [1, 2]), (1, [3])):
        sh.slices[p].append(SpillableColumnarBatch(
            ColumnarBatch.from_pydict({"a": vals}),
            OUTPUT_FOR_SHUFFLE_PRIORITY, sh.catalog))
    schema = ColumnarBatch.from_pydict({"a": [1]}).schema
    first = list(sh.read(0, schema))
    assert first and first[0].num_rows == 2
    # durable: the read did NOT close the slices — a stage retry re-reads
    again = list(sh.read(0, schema))
    assert again and again[0].num_rows == 2
    pinned = sh.pin_outputs_to_disk()
    assert pinned > 0
    assert all(s.catalog.buffers[s._id].tier is StorageTier.DISK
               for slices in sh.slices.values() for s in slices)
    # pinned outputs re-promote transparently on the next read, and the
    # read re-pins them to DISK once the batch is built — retained
    # outputs never stay device-resident after a consumer pass
    paths_before = [sh.catalog.buffers[s._id]._disk_path
                    for s in sh.slices[1]]
    after_pin = list(sh.read(1, schema))
    assert after_pin and after_pin[0].num_rows == 1
    assert all(s.catalog.buffers[s._id].tier is StorageTier.DISK
               for s in sh.slices[1])
    # the re-pin is a zero-IO tier flip: the SAME retained npz payload,
    # not a fresh D2H + savez round trip per read
    assert [sh.catalog.buffers[s._id]._disk_path
            for s in sh.slices[1]] == paths_before
    assert all(os.path.exists(p) for p in paths_before)
    sh.close_pending()
    assert all(s._closed for slices in sh.slices.values() for s in slices)


def test_pin_to_disk_failed_disk_write_keeps_accounting_consistent(
        tmp_path):
    """A disk write failing mid pin_to_disk must not tear the catalog
    byte accounting: the already-landed device->host move stays
    accounted, so later frees cannot drive host_bytes negative while
    device_bytes overcounts phantom pressure."""
    from spark_rapids_tpu.exec.spill import (BufferCatalog,
                                             SpillableColumnarBatch,
                                             StorageTier)
    cat = BufferCatalog(spill_dir=str(tmp_path / "ok"))
    s = SpillableColumnarBatch(
        ColumnarBatch.from_pydict({"a": [1, 2, 3]}), 10, cat)
    dev0, host0 = cat.device_bytes, cat.host_bytes
    cat.spill_dir = str(tmp_path / "file")   # a FILE: makedirs will fail
    (tmp_path / "file").write_text("x")
    with pytest.raises(OSError):
        cat.pin_to_disk(s._id)
    buf = cat.buffers[s._id]
    assert buf.tier is StorageTier.HOST      # host move landed...
    assert cat.device_bytes == dev0 - s.size_bytes   # ...and is accounted
    assert cat.host_bytes == host0 + s.size_bytes
    s.close()                                # removes at HOST tier
    assert cat.device_bytes == dev0 - s.size_bytes
    assert cat.host_bytes == host0           # never negative


def test_shuffle_client_retry_knobs_conf_driven():
    _session(**{"spark.rapids.tpu.sql.shuffle.fetch.maxRetries": "5",
                "spark.rapids.tpu.sql.shuffle.fetch.retryBackoff": "0.01"})
    c = ShuffleClient(lambda: (_ for _ in ()).throw(ConnectionError()))
    assert c.max_retries == 5 and c.retry_backoff_s == 0.01
    pinned = ShuffleClient(lambda: None, max_retries=1,
                           retry_backoff_s=0.5)
    assert pinned.max_retries == 1 and pinned.retry_backoff_s == 0.5


# ---------------------------------------------------------------------------
# Worker death / rejoin
# ---------------------------------------------------------------------------

def _pair(fetch_timeout_s=5.0, durable_dir=None):
    a = WorkerContext(0, 2, fetch_timeout_s=fetch_timeout_s)
    b = WorkerContext(1, 2, fetch_timeout_s=fetch_timeout_s,
                      durable_dir=durable_dir)
    a.set_peers({1: ("127.0.0.1", b.port)})
    b.set_peers({0: ("127.0.0.1", a.port)})
    return a, b


def test_mark_probe_admit_lifecycle():
    a, b = _pair()
    try:
        lost_before = _counter("tpu_worker_lost_total")
        rejoin_before = _counter("tpu_worker_rejoin_total")
        a.mark_worker_lost(1, ConnectionError("refused"))
        a.mark_worker_lost(1)                 # idempotent per episode
        assert a.is_worker_lost(1) and a.lost_workers() == [1]
        assert _counter("tpu_worker_lost_total") == lost_before + 1
        assert any("worker-lost-1" in n for n in _flight_names("recovery"))
        assert a.probe_peer(1)                # b's server is alive
        b.server.stop()
        assert not a.probe_peer(1)
        b.restart_server()
        assert a.probe_peer(1)
        a.admit_worker(1)
        assert not a.is_worker_lost(1)
        assert _counter("tpu_worker_rejoin_total") == rejoin_before + 1
        assert any("worker-rejoin-1" in n
                   for n in _flight_names("recovery"))
    finally:
        a.shutdown()
        b.shutdown()


def test_fetch_recovers_across_worker_death_and_rejoin(tmp_path):
    """The injected worker death (faults worker.die) drops the server at
    the exact protocol point; the fetching peer marks it lost, probes
    with backoff, re-admits the restarted server and re-fetches the
    DURABLE outputs — no partial rows, no query abort."""
    import threading
    _session(**{"spark.rapids.tpu.sql.recovery.maxStageRetries": "60",
                "spark.rapids.tpu.sql.recovery.retryBackoff": "0.02"})
    # fetch_timeout shorter than the rejoin delay: the completion poll
    # must EXHAUST (surfacing worker-lost) rather than silently absorb
    # the outage inside its own connect-retry window
    a, b = _pair(fetch_timeout_s=0.5, durable_dir=str(tmp_path / "w1"))
    try:
        b.store.set_fingerprint(7, "fp")
        b.store.register_batch(7, 0, _host_batch([1, 2, 3]))
        b.store.mark_complete(7)
        faults.install("worker.die")

        def die():
            b.server.stop()
            threading.Timer(1.2, b.restart_server).start()

        faults.on_fire("worker.die", die)
        lost_before = _counter("tpu_worker_lost_total")
        got = a.fetch_from_peer(1, 7, [0], fingerprint="fp")
        assert sorted(got[0].rows()) == [(1,), (2,), (3,)]
        assert faults.fired_total() == 1
        assert _counter("tpu_worker_lost_total") == lost_before + 1
        assert not a.is_worker_lost(1)        # re-admitted on success
        # the durable tier really holds the outputs: a FRESH store (true
        # process-death rejoin) re-serves them
        store2 = ShuffleStore(durable_dir=str(tmp_path / "w1"))
        assert store2.reload_durable() == 1 and store2.is_complete(7)
    finally:
        a.shutdown()
        b.shutdown()


def test_dead_worker_without_rejoin_exhausts_budget_loudly():
    _session(**{"spark.rapids.tpu.sql.recovery.maxStageRetries": "2",
                "spark.rapids.tpu.sql.recovery.retryBackoff": "0.01"})
    a, b = _pair(fetch_timeout_s=1.0)
    b.server.stop()
    try:
        with pytest.raises(ShuffleWorkerLostError) as ei:
            a.fetch_from_peer(1, 3, [0])
        assert ei.value.worker_id == 1
        assert a.is_worker_lost(1)            # stays excluded
    finally:
        a.shutdown()
        b.shutdown()


# ---------------------------------------------------------------------------
# Mesh-participant loss: ICI declines gracefully to DCN
# ---------------------------------------------------------------------------

def test_mesh_drop_declines_ici_exchange_to_dcn():
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "true"})
    df = pd.DataFrame({"k": np.arange(64, dtype="int64"),
                       "v": np.arange(64).astype("float64")})

    def planes():
        got = s.createDataFrame(df).repartition(4, col("k")).collect()
        assert len(got) == 64
        out = []

        def walk(n):
            if isinstance(n, TpuShuffleExchangeExec):
                out.append(n.plane_used)
            for c in n.children:
                walk(c)
        walk(s.last_plan())
        return out

    assert planes() == ["ici"]
    faults.install("mesh.drop")
    assert planes() == ["dcn"]                 # declined, still correct
    assert recovery.mesh_lost() is not None
    assert any("mesh-lost" in n for n in _flight_names("recovery"))
    # forced ici is a loud error while the mesh is down
    s2 = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "true",
                     "spark.rapids.tpu.sql.shuffle.plane": "ici"})
    with pytest.raises(RuntimeError, match="lost a participant"):
        s2.createDataFrame(df).repartition(4, col("k")).collect()
    recovery.clear_mesh_lost()
    assert planes() == ["ici"]                 # re-admitted


# ---------------------------------------------------------------------------
# q3-shaped chaos integration: local mode, lockdep=enforce
# ---------------------------------------------------------------------------

def _q3_frames(n=4000):
    rng = np.random.default_rng(13)
    line = pd.DataFrame({
        "l_order": rng.integers(0, 500, n).astype("int64"),
        "l_price": rng.normal(100.0, 10.0, n)})
    orders = pd.DataFrame({
        "o_key": np.arange(500, dtype="int64"),
        "o_cust": rng.integers(0, 50, 500).astype("int64"),
        "o_date": rng.integers(0, 1000, 500).astype("int64")})
    cust = pd.DataFrame({
        "c_key": np.arange(50, dtype="int64"),
        "c_seg": rng.integers(0, 3, 50).astype("int64")})
    return line, orders, cust


_Q3 = ("SELECT l_price, o_date, c_seg FROM q3_lineitem "
       "JOIN q3_orders ON l_order = o_key "
       "JOIN q3_customer ON o_cust = c_key "
       "WHERE o_date < 700 AND c_seg = 1")


def _q3_session(**extra):
    s = _session(**{
        "spark.rapids.tpu.sql.shuffle.partitions": "4",
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.tpu.sql.mesh.enabled": "false",
        "spark.rapids.tpu.sql.reader.batchSizeRows": "512",
        "spark.rapids.tpu.sql.recovery.maxStageRetries": "4",
        "spark.rapids.tpu.sql.recovery.retryBackoff": "0.0",
        "spark.rapids.tpu.sql.analysis.lockdep": "enforce",
        **extra})
    line, orders, cust = _q3_frames()
    s.createDataFrame(line).createOrReplaceTempView("q3_lineitem")
    s.createDataFrame(orders).createOrReplaceTempView("q3_orders")
    s.createDataFrame(cust).createOrReplaceTempView("q3_customer")
    return s


def test_q3_chaos_fetch_failure_and_task_poison_identical_results():
    """ISSUE 13 satellite + acceptance shape: a multi-batch q3-shaped
    3-way shuffled join completes with results IDENTICAL to the
    fault-free run under one injected mid-query fetch failure and one
    injected map-task poison, with the stage retries visible in
    telemetry and the flight record — all under lockdep=enforce."""
    s = _q3_session()
    baseline = sorted(s.sql(_Q3).collect())
    assert baseline                             # non-trivial result set
    retries_before = _counter("tpu_stage_retries_total")
    faults_before = _counter("tpu_faults_injected_total")
    faults.install("fetch.fail;task.poison@b1")
    t0 = time.perf_counter()
    got = sorted(s.sql(_Q3).collect())
    recovery_wall = time.perf_counter() - t0
    assert got == baseline
    assert faults.fired_total() == 2
    assert _counter("tpu_stage_retries_total") >= retries_before + 2
    assert _counter("tpu_faults_injected_total") == faults_before + 2
    rec = _flight_names("recovery")
    assert any(n.startswith("stage-retry-shuffle-reduce") for n in rec)
    assert any(n.startswith("stage-retry-shuffle-map") for n in rec)
    flts = _flight_names("fault")
    assert "fetch.fail" in flts and "task.poison" in flts
    assert recovery_wall < 120                  # bounded, not hung
    # the recovery-seconds histogram observed the episode
    txt = MetricsRegistry.get().prometheus_text()
    count_lines = [l for l in txt.splitlines()
                   if l.startswith("tpu_recovery_seconds_count")]
    assert count_lines and float(count_lines[0].split()[-1]) >= 1


def test_q3_durable_retry_rereads_without_map_rerun(tmp_path):
    """With the durable tier on, a consumer-side retry re-reads the
    retained slices: results identical, and the flight record shows the
    retry recovered without the refill path discarding correctness."""
    s = _q3_session(**{
        "spark.rapids.tpu.sql.shuffle.durable": "true",
        "spark.rapids.tpu.memory.spillDir": str(tmp_path)})
    baseline = sorted(s.sql(_Q3).collect())
    faults.install("fetch.fail:2")
    got = sorted(s.sql(_Q3).collect())
    assert got == baseline and faults.fired_total() == 2


# ---------------------------------------------------------------------------
# Two-process chaos: worker death + mid-window transport kill, planner-driven
# ---------------------------------------------------------------------------

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHAOS_WORKER = """
import sys, json, threading
sys.path.insert(0, {repo!r})
import os
os.environ.setdefault("SPARK_RAPIDS_TPU_COMPILE_CACHE", "off")
from spark_rapids_tpu.shuffle.manager import init_worker

wid = int(sys.argv[1]); n = int(sys.argv[2]); durable_root = sys.argv[3]
ctx = init_worker(wid, n, fetch_timeout_s=0.7,
                  durable_dir=os.path.join(durable_root, f"w{{wid}}"))
print(json.dumps({{"port": ctx.port}}), flush=True)
peers = json.loads(sys.stdin.readline())
ctx.set_peers({{int(k): tuple(v) for k, v in peers.items()}})

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col

s = TpuSession.builder.config({{
    "spark.rapids.tpu.sql.explain": "NONE",
    "spark.rapids.tpu.sql.shuffle.partitions": "4",
    "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
    "spark.rapids.tpu.sql.reader.batchSizeRows": "128",
    "spark.rapids.tpu.sql.analysis.lockdep": "enforce",
    "spark.rapids.tpu.sql.recovery.maxStageRetries": "120",
    "spark.rapids.tpu.sql.recovery.retryBackoff": "0.02",
}}).getOrCreate()

# chaos plan (armed AFTER session bootstrap so faults.refresh cannot
# clear it): worker 1 dies at its server's next connection and rejoins
# 1.5s later; a later send window tears mid-stream; worker 0 fails its
# first fetch attempt before touching the wire
from spark_rapids_tpu.analysis import faults
if wid == 1:
    faults.install("worker.die;conn.kill")

    def _die():
        ctx.server.stop()
        threading.Timer(1.5, ctx.restart_server).start()

    faults.on_fire("worker.die", _die)
else:
    faults.install("fetch.fail")

# disjoint q3-shaped shards: each table row lives on exactly ONE worker
half_o = 250; half_c = 25; n_l = 400
base_l = wid * n_l
lo = {{"l_order": [(base_l + i) % 500 for i in range(n_l)],
      "l_price": [float(i % 97) + 0.25 for i in range(n_l)]}}
oo = {{"o_key": list(range(wid * half_o, (wid + 1) * half_o)),
      "o_cust": [k % 50 for k in range(wid * half_o, (wid + 1) * half_o)]}}
cc = {{"c_key": list(range(wid * half_c, (wid + 1) * half_c)),
      "c_seg": [k % 3 for k in range(wid * half_c, (wid + 1) * half_c)]}}
s.createDataFrame(lo).createOrReplaceTempView("cl")
s.createDataFrame(oo).createOrReplaceTempView("co")
s.createDataFrame(cc).createOrReplaceTempView("cc")

out = (s.table("cl")
       .join(s.table("co"), on=(col("l_order") == col("o_key")),
             how="inner")
       .join(s.table("cc"), on=(col("o_cust") == col("c_key")),
             how="inner")
       .groupBy("c_seg")
       .agg(F.sum(col("l_price")).alias("rev"))
       .collect())

from spark_rapids_tpu.service.telemetry import FlightRecorder, MetricsRegistry
reg = MetricsRegistry.get()

def cval(nm):
    return float(reg.counter(nm, "x").value)

ev = FlightRecorder.get().events()
print(json.dumps({{
    "rows": [list(r) for r in out],
    "stage_retries": cval("tpu_stage_retries_total"),
    "worker_lost": cval("tpu_worker_lost_total"),
    "worker_rejoin": cval("tpu_worker_rejoin_total"),
    "faults": faults.fired_total(),
    "recovery_events": sorted({{e["name"] for e in ev
                               if e["kind"] == "recovery"}}),
    "fault_events": sorted({{e["name"] for e in ev
                            if e["kind"] == "fault"}})}}), flush=True)
ctx.shutdown()
"""


def _chaos_oracle():
    """Pandas oracle over the union of both workers' disjoint shards."""
    frames_l, frames_o, frames_c = [], [], []
    for wid in range(2):
        base_l = wid * 400
        frames_l.append(pd.DataFrame({
            "l_order": [(base_l + i) % 500 for i in range(400)],
            "l_price": [float(i % 97) + 0.25 for i in range(400)]}))
        okeys = list(range(wid * 250, (wid + 1) * 250))
        frames_o.append(pd.DataFrame(
            {"o_key": okeys, "o_cust": [k % 50 for k in okeys]}))
        ckeys = list(range(wid * 25, (wid + 1) * 25))
        frames_c.append(pd.DataFrame(
            {"c_key": ckeys, "c_seg": [k % 3 for k in ckeys]}))
    j = (pd.concat(frames_l)
         .merge(pd.concat(frames_o), left_on="l_order", right_on="o_key")
         .merge(pd.concat(frames_c), left_on="o_cust", right_on="c_key"))
    return {int(k): float(v)
            for k, v in j.groupby("c_seg").l_price.sum().items()}


def test_two_process_chaos_worker_death_and_conn_kill(tmp_path):
    """ISSUE 13 acceptance: a multi-batch q3-shaped shuffled join across
    two OS processes, green under lockdep=enforce, with an injected
    WORKER DEATH (+1.5s rejoin) and an injected MID-WINDOW TRANSPORT
    KILL on worker 1 plus a first-attempt fetch failure on worker 0 —
    returns results identical to the fault-free oracle, with >=1 stage
    retry and >=1 worker-lost (and rejoin) event visible in telemetry
    and the flight record."""
    import json
    import subprocess
    import sys
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHAOS_WORKER.format(repo=_REPO),
         str(wid), "2", str(tmp_path)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, env=env, text=True) for wid in range(2)]
    try:
        ports = {}
        for wid, p in enumerate(procs):
            line = p.stdout.readline()
            assert line, p.stderr.read()
            ports[wid] = ("127.0.0.1", json.loads(line)["port"])
        peers = json.dumps({str(w): list(a) for w, a in ports.items()})
        for p in procs:
            p.stdin.write(peers + "\n")
            p.stdin.flush()
        reports = {}
        for wid, p in enumerate(procs):
            out, err = p.communicate(timeout=280)
            assert p.returncode == 0, err[-4000:]
            for line in out.splitlines():
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "rows" in d:
                    reports[wid] = d
        assert set(reports) == {0, 1}
        # identical to the fault-free run: union of owned partitions
        # equals the pandas oracle over the union of shards
        got = {}
        for d in reports.values():
            for k, v in d["rows"]:
                assert k not in got      # each group owned exactly once
                got[int(k)] = float(v)
        oracle = _chaos_oracle()
        assert set(got) == set(oracle)
        for k in oracle:
            assert abs(got[k] - oracle[k]) <= 1e-6 * max(1.0, oracle[k])
        # every armed fault fired: death + torn window on w1, fetch on w0
        assert reports[0]["faults"] == 1
        assert "fetch.fail" in reports[0]["fault_events"]
        assert reports[1]["faults"] == 2
        assert "worker.die" in reports[1]["fault_events"]
        assert "conn.kill" in reports[1]["fault_events"]
        # the recovery trail: worker 0 lost its peer, retried the fetch
        # stage, and re-admitted the rejoined worker
        assert reports[0]["stage_retries"] >= 1
        assert reports[0]["worker_lost"] >= 1
        assert reports[0]["worker_rejoin"] >= 1
        rec = reports[0]["recovery_events"]
        assert any(n.startswith("worker-lost-1") for n in rec)
        assert any(n.startswith("worker-rejoin-1") for n in rec)
        assert any(n.startswith("stage-retry-") for n in rec)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
