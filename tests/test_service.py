"""Multi-tenant query service: admission, budgets, scheduling, replay.

Covers ISSUE 15 (docs/service.md): typed admission rejects and
deadline fail-fast, priority no-starvation, per-tenant device-byte
budgets with over-budget-spills-first ordering, per-tenant watermarks
returning to zero, tenant-tagged query-log/flight records, the SQL-text
parse cache, and the traffic-replay bench feeding the history gate —
the concurrent-load shape tier-1 could not see before this PR.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.spill import (BufferCatalog, SpillableColumnarBatch,
                                         StorageTier)
from spark_rapids_tpu.service import tenants as tn
from spark_rapids_tpu.service.server import (AdmissionRejected,
                                             DeadlineExceededError,
                                             QueryService, ServiceClosed,
                                             TenantSpec)


def _session(extra=None):
    from spark_rapids_tpu.api.session import TpuSession
    conf = {
        "spark.rapids.tpu.sql.explain": "NONE",
        "spark.rapids.tpu.sql.analysis.lockdep": "enforce",
    }
    conf.update(extra or {})
    return TpuSession.builder.config(conf).getOrCreate()


def _mk_batch(n=256):
    schema = dt.Schema([dt.Field("v", dt.FLOAT64)])
    return ColumnarBatch.from_pydict(
        {"v": np.arange(n, dtype=np.float64)}, schema)


@pytest.fixture(autouse=True)
def _clean_budgets():
    tn.reset_budgets()
    yield
    tn.reset_budgets()


# ---------------------------------------------------------------------------
# Per-tenant memory budgets (exec/spill.py)
# ---------------------------------------------------------------------------

def test_over_budget_tenant_spills_its_own_buffers_first():
    _session()
    BufferCatalog.reset()
    cat = BufferCatalog.get()
    one = _mk_batch().device_size_bytes()
    tn.set_budget("bronze", int(one * 1.5))   # second buffer overdraws
    with tn.tenant_scope("gold"):
        g = SpillableColumnarBatch(_mk_batch())
    with tn.tenant_scope("bronze"):
        b1 = SpillableColumnarBatch(_mk_batch())
        b2 = SpillableColumnarBatch(_mk_batch())
    # bronze went over budget at b2's REGISTER: its oldest buffer spilled
    # while the just-registered batch stayed (never its own victim) and
    # gold was untouched
    assert cat.buffers[b1._id].tier == StorageTier.HOST
    assert cat.buffers[b2._id].tier == StorageTier.DEVICE
    assert cat.buffers[g._id].tier == StorageTier.DEVICE
    held = cat.tenant_device_bytes()
    assert held["bronze"] <= int(one * 1.5)
    assert held["gold"] == one
    for h in (g, b1, b2):
        h.close()
    assert cat.tenant_device_bytes() == {}     # watermarks return to 0


def test_global_cascade_prefers_over_budget_tenants():
    _session()
    BufferCatalog.reset()
    cat = BufferCatalog.get()
    one = _mk_batch().device_size_bytes()
    # bronze unenforced-at-register... budget bigger than one buffer but
    # smaller than two, gold unbudgeted; then GLOBAL pressure must pick
    # bronze's buffers first even though gold's are older/lower priority
    tn.set_budget("bronze", int(one * 1.5))
    with tn.tenant_scope("gold"):
        g1 = SpillableColumnarBatch(_mk_batch(), priority=-10.0)
    with tn.tenant_scope("bronze"):
        b1 = SpillableColumnarBatch(_mk_batch(), priority=50.0)
    tn.set_budget("bronze", 1)                # NOW bronze is over budget
    cat.device_budget = int(one * 1.5)        # global pressure: one must go
    cat.reserve(0)
    assert cat.buffers[b1._id].tier == StorageTier.HOST, \
        "over-budget bronze must be the cascade victim despite gold's " \
        "lower spill priority"
    assert cat.buffers[g1._id].tier == StorageTier.DEVICE
    for h in (g1, b1):
        h.close()
    assert cat.tenant_device_bytes() == {}


def test_cache_priority_registrations_stay_untenanted():
    from spark_rapids_tpu.exec.spill import CACHE_PRIORITY
    _session()
    BufferCatalog.reset()
    cat = BufferCatalog.get()
    with tn.tenant_scope("gold"):
        h = SpillableColumnarBatch(_mk_batch(), CACHE_PRIORITY)
    assert cat.tenant_device_bytes() == {}, \
        "shared cache entries must not pin a tenant's watermark"
    h.close()


# ---------------------------------------------------------------------------
# Admission control + scheduling (service/server.py)
# ---------------------------------------------------------------------------

def test_admission_reject_typed_and_counted():
    session = _session()
    svc = QueryService(session, tenants=[
        TenantSpec("bronze", priority=0, slots=1, max_queue_depth=1)],
        max_workers=2)
    gate = threading.Event()
    running = threading.Event()

    def blocker():
        running.set()
        gate.wait(10)
        return "done"

    try:
        t_run = svc.submit("bronze", blocker)
        assert running.wait(5)
        t_q = svc.submit("bronze", lambda: "queued")   # fills the queue
        with pytest.raises(AdmissionRejected) as ei:
            svc.submit("bronze", lambda: "shed")
        assert ei.value.tenant == "bronze"
        gate.set()
        assert t_run.result(timeout=10) == "done"
        assert t_q.result(timeout=10) == "queued"
        st = svc.stats()["tenants"]["bronze"]
        assert st["rejected"] == 1 and st["completed"] == 2
    finally:
        gate.set()
        svc.close()


def test_deadline_fail_fast_without_occupying_a_slot():
    from spark_rapids_tpu.service.telemetry import FlightRecorder
    session = _session()
    svc = QueryService(session, tenants=[TenantSpec("t", slots=1)],
                       max_workers=1)
    gate = threading.Event()
    running = threading.Event()
    ran = []

    def blocker():
        running.set()
        gate.wait(10)

    try:
        # already-lapsed deadline: rejected AT submit, typed
        with pytest.raises(DeadlineExceededError):
            svc.submit("t", lambda: ran.append(1), deadline_s=0)
        svc.submit("t", blocker)
        assert running.wait(5)
        doomed = svc.submit("t", lambda: ran.append(2), deadline_s=0.05)
        time.sleep(0.5)                       # lapses while queued
        gate.set()
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=10)
        assert ran == [], "deadline-shed queries must never run"
        assert svc.stats()["tenants"]["t"]["deadlineExpired"] == 2
        events = [e for e in FlightRecorder.get().events()
                  if e["kind"] == "admission" and
                  e["name"] == "deadline-shed"]
        assert events and events[-1]["data"]["tenant"] == "t"
    finally:
        gate.set()
        svc.close()


def test_low_priority_flood_cannot_starve_high_priority():
    session = _session()
    svc = QueryService(session, tenants=[
        TenantSpec("hi", priority=10, slots=4),
        TenantSpec("lo", priority=0, slots=4)], max_workers=1)
    gate = threading.Event()
    running = threading.Event()

    def blocker():
        running.set()
        gate.wait(10)

    try:
        svc.submit("lo", blocker)
        assert running.wait(5)
        flood = [svc.submit("lo", lambda i=i: f"lo{i}") for i in range(6)]
        urgent = [svc.submit("hi", lambda i=i: f"hi{i}") for i in range(2)]
        gate.set()
        for t in urgent + flood:
            t.result(timeout=30)
        # strict priority: every queued high-priority query ran before
        # any of the queued flood
        assert max(t.finished_at for t in urgent) < \
            min(t.finished_at for t in flood)
    finally:
        gate.set()
        svc.close()


def test_service_close_fails_pending_typed():
    session = _session()
    svc = QueryService(session, tenants=[TenantSpec("t", slots=1)],
                       max_workers=1)
    gate = threading.Event()
    running = threading.Event()

    def blocker():
        running.set()
        gate.wait(10)

    svc.submit("t", blocker)
    assert running.wait(5)
    pending = svc.submit("t", lambda: "never")
    gate.set()
    svc.close()
    with pytest.raises((ServiceClosed, AdmissionRejected)):
        pending.result(timeout=5)
    with pytest.raises(AdmissionRejected):
        svc.submit("t", lambda: "after-close")


# ---------------------------------------------------------------------------
# Multi-tenant stress under lockdep=enforce
# ---------------------------------------------------------------------------

def test_multi_tenant_stress_lockdep_enforce():
    """N threads x M tenants hammering ONE engine under enforce: no lock
    inversion (enforce raises), correct results everywhere, typed
    rejects only, per-tenant watermarks back to 0."""
    session = _session()
    df = session.createDataFrame({
        "k": [i % 7 for i in range(500)],
        "v": [float(i) for i in range(500)]})
    df.createOrReplaceTempView("stress_t")
    expected_sum = session.sql(
        "SELECT sum(v) AS s FROM stress_t").collect()
    expected_grp = session.sql(
        "SELECT k, count(*) AS n FROM stress_t GROUP BY k ORDER BY k"
    ).collect()
    svc = QueryService(session, tenants=[
        TenantSpec("a", priority=5, slots=2, max_queue_depth=64,
                   memory_budget_bytes=64 << 20),
        TenantSpec("b", priority=0, slots=2, max_queue_depth=64,
                   memory_budget_bytes=32 << 20),
        TenantSpec("c", priority=10, slots=1, max_queue_depth=64)],
        max_workers=4)
    errors = []
    mu = threading.Lock()

    def hammer(tenant, n):
        for i in range(n):
            sql = ("SELECT sum(v) AS s FROM stress_t" if i % 2 == 0 else
                   "SELECT k, count(*) AS n FROM stress_t GROUP BY k "
                   "ORDER BY k")
            want = expected_sum if i % 2 == 0 else expected_grp
            try:
                got = svc.submit(tenant, sql).result(timeout=120).rows()
                if got != want:
                    with mu:
                        errors.append(f"{tenant}/{i}: wrong rows {got}")
            except AdmissionRejected:
                pass                      # typed back-pressure is legal
            except Exception as e:
                with mu:
                    errors.append(f"{tenant}/{i}: {type(e).__name__}: {e}")

    try:
        threads = [threading.Thread(target=hammer, args=(t, 8))
                   for t in ("a", "b", "c") for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:5]
        stats = svc.stats()
        done = sum(s["completed"] for s in stats["tenants"].values())
        assert done >= 40                  # 48 submitted, rejects legal
        assert stats["queued"] == 0 and stats["running"] == 0
    finally:
        svc.close()
    cat = BufferCatalog.peek()
    if cat is not None:
        held = cat.tenant_device_bytes()
        assert all(v == 0 for v in held.values()) or held == {}, held


# ---------------------------------------------------------------------------
# Acceptance: two tenants, concurrent TPC-H-shaped queries, telemetry
# ---------------------------------------------------------------------------

def test_acceptance_two_tenants_concurrent_tpch(tmp_path):
    from benchmarks import datagen
    from spark_rapids_tpu.service.telemetry import MetricsRegistry
    from tools import query_report
    log_dir = str(tmp_path / "qlog")
    session = _session({
        "spark.rapids.tpu.sql.telemetry.queryLog.dir": log_dir})
    tables = datagen.register_tables(session, 0.0005)
    tables["lineitem"].createOrReplaceTempView("acc_lineitem")
    q6 = ("SELECT sum(l_extendedprice * l_discount) AS revenue "
          "FROM acc_lineitem WHERE l_shipdate >= 8766 AND "
          "l_shipdate < 9131 AND l_discount >= 0.05 AND "
          "l_discount <= 0.07 AND l_quantity < 24")
    grp = ("SELECT l_returnflag, count(*) AS n FROM acc_lineitem "
           "GROUP BY l_returnflag ORDER BY l_returnflag")
    want = {"q6": session.sql(q6).collect(),
            "grp": session.sql(grp).collect()}
    svc = QueryService(session, tenants=[
        TenantSpec("gold", priority=10, slots=2,
                   memory_budget_bytes=1 << 30),
        TenantSpec("bronze", priority=0, slots=2,
                   memory_budget_bytes=16 << 20)])
    try:
        tickets = [
            svc.submit("gold", q6, label="gold-q6"),
            svc.submit("bronze", grp, label="bronze-grp"),
            svc.submit("gold", grp, label="gold-grp"),
            svc.submit("bronze", q6, label="bronze-q6"),
            svc.submit("gold", q6, label="gold-q6b"),
        ]
        rows = [t.result(timeout=120).rows() for t in tickets]
        assert rows[0] == want["q6"] and rows[3] == want["q6"] \
            and rows[4] == want["q6"]
        assert rows[1] == want["grp"] and rows[2] == want["grp"]
        stats = svc.stats()["tenants"]
        assert stats["gold"]["admitted"] == 3
        assert stats["bronze"]["admitted"] == 2
        # per-tenant queue/admission telemetry series exist and count
        reg = MetricsRegistry.get()
        for tenant, n in (("gold", 3), ("bronze", 2)):
            assert reg.counter("tpu_tenant_admitted_total", "x",
                               tenant=tenant).value >= n
        # per-tenant device-byte gauge rides the harvest surface: hold a
        # buffer under a tenant scope across a scrape, then release and
        # scrape again — the gauge must show the bytes, then return to 0
        with tn.tenant_scope("gold"):
            held = SpillableColumnarBatch(_mk_batch())
        text = session.prometheus_metrics()
        assert 'tpu_tenant_device_bytes{tenant="gold"}' in text
        held.close()
        text = session.prometheus_metrics()
        assert 'tpu_tenant_device_bytes{tenant="gold"} 0' in text
        assert "tpu_query_queue_seconds" in text
    finally:
        svc.close()
    # tenant-tagged query-log records + the per-tenant report rollup
    files = [os.path.join(log_dir, f) for f in os.listdir(log_dir)]
    recs = [json.loads(line) for f in files for line in open(f)]
    by_tenant = {}
    for r in recs:
        if r.get("tenant"):
            by_tenant.setdefault(r["tenant"], []).append(r)
    assert len(by_tenant.get("gold", [])) == 3
    assert len(by_tenant.get("bronze", [])) == 2
    assert all(r["queryId"] for r in recs)
    rendered = query_report.render(files)
    assert "per-tenant summary" in rendered
    assert "gold: queries=3" in rendered
    assert "bronze: queries=2" in rendered


def test_flight_events_carry_tenant_next_to_query_id():
    from spark_rapids_tpu.service.telemetry import FlightRecorder
    session = _session()
    df = session.createDataFrame({"v": [1.0, 2.0, 3.0]})
    df.createOrReplaceTempView("fr_t")
    with tn.tenant_scope("acme"):
        session.sql("SELECT sum(v) AS s FROM fr_t").collect()
    tagged = [e for e in FlightRecorder.get().events()
              if (e.get("data") or {}).get("tenant") == "acme"]
    assert tagged, "query events inside a tenant scope must be tagged"
    assert all(e["data"].get("query") for e in tagged
               if e["kind"] == "span")


# ---------------------------------------------------------------------------
# SQL-text parse cache (PR 12 follow-up)
# ---------------------------------------------------------------------------

def test_parse_cache_hit_miss_and_invalidation():
    session = _session()
    df = session.createDataFrame({"k": [1, 2], "v": [1.0, 2.0]})
    df.createOrReplaceTempView("pc_t")
    q = "SELECT k, sum(v) AS s FROM pc_t GROUP BY k ORDER BY k"
    base = dict(session.serving_stats())
    r1 = session.sql(q).collect()
    r2 = session.sql(q).collect()
    st = session.serving_stats()
    assert r1 == r2
    assert st["parses"] - base["parses"] == 1
    assert st["parseCacheHits"] - base["parseCacheHits"] == 1
    assert st["parseCacheMisses"] - base["parseCacheMisses"] == 1
    # re-registering a referenced view invalidates the cached parse
    session.createDataFrame({"k": [1], "v": [7.0]}) \
        .createOrReplaceTempView("pc_t")
    assert session.sql(q).collect() == [(1, 7.0)]
    st2 = session.serving_stats()
    assert st2["parses"] - st["parses"] == 1


def test_parse_cache_conf_disables():
    session = _session({
        "spark.rapids.tpu.sql.service.parseCache.maxEntries": "0"})
    session.createDataFrame({"v": [1.0]}).createOrReplaceTempView("pd_t")
    q = "SELECT sum(v) AS s FROM pd_t"
    session.sql(q).collect()
    session.sql(q).collect()
    st = session.serving_stats()
    assert st["parseCacheHits"] == 0
    assert st["parses"] >= 2


# ---------------------------------------------------------------------------
# Concurrent plan-cache exclusivity (the serving substrate under load)
# ---------------------------------------------------------------------------

def test_concurrent_same_fingerprint_queries_stay_correct():
    """Two threads executing the SAME parameterized shape with different
    literals concurrently: the busy entry must never serve both (one
    plans fresh), and each must get its own literals' result."""
    session = _session()
    session.createDataFrame({
        "k": list(range(100)),
        "v": [float(i) for i in range(100)]}).createOrReplaceTempView(
        "cc_t")
    done = []
    errors = []
    barrier = threading.Barrier(2)

    def run(lo, want_n):
        try:
            barrier.wait(5)
            for _ in range(5):
                rows = session.sql(
                    f"SELECT count(*) AS n FROM cc_t WHERE k >= {lo}"
                ).collect()
                if rows != [(want_n,)]:
                    errors.append((lo, rows))
            done.append(lo)
        except Exception as e:
            errors.append((lo, f"{type(e).__name__}: {e}"))

    t1 = threading.Thread(target=run, args=(10, 90))
    t2 = threading.Thread(target=run, args=(60, 40))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert not errors, errors[:3]
    assert sorted(done) == [10, 60]


def test_concurrent_cte_parses_do_not_corrupt_the_catalog():
    """parse_sql registers CTEs as query-scoped temp views in the SHARED
    session catalog and restores it: interleaved from two service
    workers that save/mutate/restore used to leak one parse's CTE into
    the session (review finding, pinned here)."""
    session = _session()
    session.createDataFrame({"v": [1.0, 2.0, 3.0]}) \
        .createOrReplaceTempView("cte_base")
    views_before = dict(session._views)
    errors = []
    barrier = threading.Barrier(4)

    def run(i):
        try:
            barrier.wait(5)
            for j in range(6):
                got = session.sql(
                    f"WITH c{i} AS (SELECT v + {i} AS w FROM cte_base) "
                    f"SELECT sum(w) AS s FROM c{i}").collect()
                if got != [(6.0 + 3 * i,)]:
                    errors.append((i, j, got))
        except Exception as e:
            errors.append((i, f"{type(e).__name__}: {e}"))

    threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    assert set(session._views) == set(views_before), \
        "CTE temp views leaked into (or vanished from) the catalog"


def test_ticket_query_id_is_this_execution_not_last_writer():
    session = _session()
    session.createDataFrame({"v": [float(i) for i in range(200)]}) \
        .createOrReplaceTempView("qid_t")
    svc = QueryService(session, tenants=[TenantSpec("t", slots=4)],
                       max_workers=4)
    try:
        tickets = [svc.submit("t", f"SELECT sum(v + {i}) AS s FROM qid_t")
                   for i in range(6)]
        for t in tickets:
            t.result(timeout=120)
        qids = [t.query_id for t in tickets]
        assert all(qids), qids
        assert len(set(qids)) == len(qids), \
            f"concurrent tickets shared a query id: {qids}"
    finally:
        svc.close()


def test_register_tenant_update_preserves_live_accounting():
    session = _session()
    svc = QueryService(session, tenants=[
        TenantSpec("t", priority=1, slots=1, max_queue_depth=8)],
        max_workers=2)
    gate = threading.Event()
    running = threading.Event()

    def blocker():
        running.set()
        gate.wait(10)
        return "ok"

    try:
        t_run = svc.submit("t", blocker)
        assert running.wait(5)
        # live update: raise the slot bound + change priority while a
        # query runs — counters must carry over (running stays 1, the
        # admitted count survives)
        state = svc.register_tenant(TenantSpec("t", priority=9, slots=3))
        assert state.running == 1 and state.admitted == 1
        assert state.slots == 3 and state.priority == 9
        t2 = svc.submit("t", lambda: "second")   # admitted on new slots
        assert t2.result(timeout=10) == "second"
        gate.set()
        assert t_run.result(timeout=10) == "ok"
        st = svc.stats()["tenants"]["t"]
        assert st["completed"] == 2 and st["running"] == 0
    finally:
        gate.set()
        svc.close()


def test_tenant_rollup_counts_multiworker_query_once():
    from tools.query_report import tenant_rollup
    recs = [
        {"tenant": "gold", "queryId": "q1", "wallS": 2.0, "rows": 10,
         "stageRetries": 1},
        {"tenant": "gold", "queryId": "q1", "wallS": 1.5, "rows": 12,
         "stageRetries": 0},
        {"tenant": "gold", "queryId": "q2", "wallS": 0.5, "rows": 1,
         "stageRetries": 0},
    ]
    out = tenant_rollup(recs)
    assert "gold: queries=2" in out
    assert "wallS=2.5" in out            # max per query, summed
    assert "rows=23" in out


def test_tenant_rollup_counts_lifecycle_transitions():
    """A query with suspend/resume (or cancel) transitions in its
    query-log ``lifecycle`` field counts ONCE per tenant, regardless of
    cycles or worker records; plain queries add no lifecycle columns."""
    from tools.query_report import tenant_rollup
    cyc = [{"state": "running"}, {"state": "suspend-requested"},
           {"state": "suspended"}, {"state": "resumed"},
           {"state": "suspended"}, {"state": "resumed"}]
    recs = [
        {"tenant": "bronze", "queryId": "q1", "wallS": 1.0, "rows": 5,
         "lifecycle": cyc},
        {"tenant": "bronze", "queryId": "q1", "wallS": 1.0, "rows": 5,
         "lifecycle": cyc},                       # second worker record
        {"tenant": "bronze", "queryId": "q2", "wallS": 0.2, "rows": 0,
         "lifecycle": [{"state": "running"}, {"state": "cancelled"}]},
        {"tenant": "gold", "queryId": "q3", "wallS": 0.1, "rows": 1},
    ]
    out = tenant_rollup(recs)
    assert "preempted=1" in out          # two cycles, one query
    assert "cancelled=1" in out
    gold_line = [l for l in out.splitlines() if "gold:" in l][0]
    assert "preempted" not in gold_line and "cancelled" not in gold_line


# ---------------------------------------------------------------------------
# Traffic-replay bench -> history gate
# ---------------------------------------------------------------------------

def test_replay_bench_stamps_accepted_gate_entry(tmp_path):
    from benchmarks import history as bh
    from benchmarks.replay import run_replay
    hist = str(tmp_path / "hist.jsonl")
    line = run_replay(sf=0.0005, streams=2, queries_per_stream=2,
                      stamp=True, history_path=hist)
    assert line["replay_ok"], line
    assert line["completed"] == 4
    assert line["replay_qps"] > 0
    assert 0 < line["replay_p50_s"] <= line["replay_p99_s"]
    assert line["regression_overall"] == "no-baseline"
    rounds = bh.load(hist)
    assert len(rounds) == 1 and rounds[0]["kind"] == "replay"
    assert set(rounds[0]["queries"]) == {
        bh.REPLAY_QPS, bh.REPLAY_P50_S, bh.REPLAY_P99_S,
        bh.FIRST_ROW_P99_S}
    # latency percentiles are recorded direction-inverted (lower is
    # better) — including the streamed-leg first-row p99 (ISSUE 17)
    assert set(rounds[0]["invertedQueries"]) == {
        bh.REPLAY_P50_S, bh.REPLAY_P99_S, bh.FIRST_ROW_P99_S}
    assert line["streaming_queries"] == 2      # one streamed per stream
    assert 0 < line["first_row_p50_s"] <= line["first_row_p99_s"]
    # a second round is judged against the first (accepted by the gate)
    line2 = run_replay(sf=0.0005, streams=2, queries_per_stream=2,
                       stamp=True, history_path=hist)
    assert line2["replay_ok"]
    assert set(line2["regression"]) == {
        bh.REPLAY_QPS, bh.REPLAY_P50_S, bh.REPLAY_P99_S,
        bh.FIRST_ROW_P99_S}
    assert all(v in ("ok", "warn", "fail", "improvement")
               for v in line2["regression"].values())


def test_replay_chaos_mode_bounded_recovery(tmp_path):
    from benchmarks import history as bh
    from benchmarks.replay import run_replay
    hist = str(tmp_path / "hist.jsonl")
    line = run_replay(sf=0.0005, streams=2, queries_per_stream=2,
                      faults="fetch.fail;task.poison", stamp=True,
                      history_path=hist)
    assert line["replay_ok"], line
    assert line["faults_fired"] >= 2
    assert line["stage_retries"] >= 1
    assert line["replay_chaos_p99_s"] > 0
    rounds = bh.load(hist)
    assert set(rounds[0]["queries"]) == {bh.REPLAY_CHAOS_P99_S}


def test_preempt_replay_end_to_end_acceptance(tmp_path):
    """ISSUE 20 acceptance: the preemption-armed mixed-priority leg —
    a running low-priority query is suspended by a high-priority
    arrival which completes first; the preempted query resumes with
    oracle-correct rows; tenant watermarks return to zero (the leg runs
    under bufferLedger=enforce, so leaked buffers raise); and the gold
    p99 stamps the history gate direction-inverted."""
    from benchmarks import history as bh
    from benchmarks.replay import run_preempt_replay
    hist = str(tmp_path / "hist.jsonl")
    line = run_preempt_replay(sf=0.0005, rounds=2, stamp=True,
                              history_path=hist)
    assert line["replay_ok"], line
    # honesty: >=1 OBSERVED suspend/resume cycle, not just armed
    assert line["preempted"] >= 1 and line["resumed"] >= 1
    assert line["gold_completed"] == 2
    assert line["replay_preempt_p99_s"] > 0
    tenants = line["service"]["tenants"]
    assert tenants["bronze"]["preempted"] == line["preempted"]
    assert tenants["bronze"]["completed"] == 2   # resumed AND finished
    assert tenants["gold"]["preempted"] == 0     # only bronze parks
    for t in ("gold", "bronze"):
        assert tenants[t]["deviceBytes"] == 0    # watermarks at zero
    assert line["service"]["suspended"] == 0     # nothing left parked
    rounds = bh.load(hist)
    assert len(rounds) == 1
    assert set(rounds[0]["queries"]) == {bh.REPLAY_PREEMPT_P99_S}
    assert bh.REPLAY_PREEMPT_P99_S in rounds[0]["invertedQueries"]
