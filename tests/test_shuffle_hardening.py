"""Multi-worker shuffle contract hardening (VERDICT round-4 item 3):
the lockstep shuffle-id contract must fail LOUDLY, never silently pair
mismatched shuffles or return partial rows.

- fingerprint handshake: a worker whose query stream diverged gets
  ShuffleDesyncError on its first metadata round trip (the reference
  cannot hit this class — the driver issues shuffle ids; standalone,
  the structural-fingerprint check replaces the driver).
- worker loss: a dead peer surfaces ShuffleWorkerLostError naming the
  peer (RapidsShuffleIterator FetchFailed contract, loud-abort form —
  a lost worker's local shard has no other lineage to recompute from).
- release quorum: shuffle outputs free once EVERY worker acked done-
  reading (ShuffleBufferCatalog active-shuffle lifecycle; previously a
  no-op that accumulated outputs until shutdown).
- control-plane allreduce: the primitive behind mesh-consistent AQE
  decisions (every worker computes the same global build size).
"""

import threading

import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.shuffle.manager import DistributedShuffle, WorkerContext
from spark_rapids_tpu.shuffle.transport import (ShuffleDesyncError,
                                                ShuffleFetchError,
                                                ShuffleWorkerLostError)


def _pair(fetch_timeout_s: float = 5.0):
    """Two in-process worker contexts wired as peers (not installed as
    WorkerContext.current: the planner must stay in local mode)."""
    a = WorkerContext(0, 2, fetch_timeout_s=fetch_timeout_s)
    b = WorkerContext(1, 2, fetch_timeout_s=fetch_timeout_s)
    a.set_peers({1: ("127.0.0.1", b.port)})
    b.set_peers({0: ("127.0.0.1", a.port)})
    return a, b


def _host_batch(vals):
    return ColumnarBatch.from_pydict({"a": list(vals)}).fetch_to_host()


def _wait_until(cond, timeout_s=5.0):
    """Release acks are fire-and-forget and land on server threads:
    poll briefly instead of asserting a racy instant."""
    import time
    deadline = time.monotonic() + timeout_s
    while not cond():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.02)
    return True


def test_fingerprint_desync_fails_loudly():
    """Peer registered shuffle 5 under a different plan fingerprint: the
    fetch aborts immediately with ShuffleDesyncError (no retry, no poll
    — waiting cannot un-diverge query streams)."""
    a, b = _pair()
    try:
        b.store.set_fingerprint(5, "fp-worker-b")
        b.store.register_batch(5, 0, _host_batch([1, 2, 3]))
        b.store.mark_complete(5)
        with pytest.raises(ShuffleDesyncError, match="diverged"):
            a.fetch_from_peer(1, 5, [0], fingerprint="fp-worker-a")
    finally:
        a.shutdown()
        b.shutdown()


def test_matching_fingerprint_fetch_succeeds():
    a, b = _pair()
    try:
        b.store.set_fingerprint(5, "fp-same")
        b.store.register_batch(5, 0, _host_batch([1, 2, 3]))
        b.store.mark_complete(5)
        got = a.fetch_from_peer(1, 5, [0], fingerprint="fp-same")
        assert len(got) == 1 and sorted(got[0].rows()) == [(1,), (2,), (3,)]
    finally:
        a.shutdown()
        b.shutdown()


def test_dead_worker_fails_loudly_naming_peer():
    """A peer whose server died surfaces ShuffleWorkerLostError carrying
    the peer's id — the query aborts instead of returning partial rows."""
    a, b = _pair(fetch_timeout_s=1.0)
    b.server.stop()
    try:
        with pytest.raises(ShuffleWorkerLostError) as ei:
            a.fetch_from_peer(1, 3, [0])
        assert ei.value.worker_id == 1
        assert "worker 1" in str(ei.value)
    finally:
        a.shutdown()
        b.shutdown()


def test_release_quorum_frees_outputs_everywhere():
    """close_pending releases: nothing frees until ALL workers acked
    done-reading; once the quorum completes, every store drops the
    shuffle's buffers (no accumulation until shutdown)."""
    a, b = _pair()
    try:
        sha = DistributedShuffle(4, a, fingerprint="fp-q")
        shb = DistributedShuffle(4, b, fingerprint="fp-q")
        assert sha.shuffle_id == shb.shuffle_id        # lockstep
        a.store.register_batch(sha.shuffle_id, 0, _host_batch([1]))
        b.store.register_batch(shb.shuffle_id, 1, _host_batch([2]))
        sha.finish_writes()
        shb.finish_writes()
        # worker A reads its owned partition (local + peer), then acks
        got = list(sha.read(1, _host_batch([0]).schema))
        assert got and sorted(got[0].rows()) == [(2,)]
        sha.close_pending()
        # half-quorum: B's outputs must still be fetchable by... no one
        # new, but they must not be freed yet (A acked, B did not)
        assert b.store.buffer_count() == 1
        assert not b.store.is_released(shb.shuffle_id)
        shb.close_pending()
        assert _wait_until(lambda: a.store.buffer_count() == 0)
        assert _wait_until(lambda: b.store.buffer_count() == 0)
        assert a.store.is_released(sha.shuffle_id)
        # a fetch after the quorum released is LOUD, not empty/wrong
        with pytest.raises(ShuffleFetchError, match="released"):
            a.fetch_from_peer(1, shb.shuffle_id, [0], fingerprint="fp-q")
    finally:
        a.shutdown()
        b.shutdown()


def test_allreduce_bytes_sums_on_every_worker():
    """The control-plane allreduce: both workers compute the SAME global
    total (the primitive behind mesh-consistent AQE branch decisions),
    and the control values release themselves after use."""
    a, b = _pair()
    try:
        out = {}

        def run(ctx, v):
            out[ctx.worker_id] = ctx.allreduce_bytes(99, v)
        ta = threading.Thread(target=run, args=(a, 1000))
        tb = threading.Thread(target=run, args=(b, 234))
        ta.start()
        tb.start()
        ta.join(20)
        tb.join(20)
        assert out == {0: 1234, 1: 1234}
        assert _wait_until(lambda: a.store.buffer_count() == 0)
        assert _wait_until(lambda: b.store.buffer_count() == 0)
    finally:
        a.shutdown()
        b.shutdown()


def test_plan_fingerprint_structural():
    """Same logical query -> same exchange fingerprint on every worker;
    structurally different exchanges -> different fingerprints (the
    desync signature)."""
    from spark_rapids_tpu.api.session import TpuSession

    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE",
         "spark.rapids.tpu.sql.shuffle.partitions": "4"}).getOrCreate()
    s.createDataFrame({"k": [1, 2, 3, 1], "v": [1.0, 2.0, 3.0, 4.0]}) \
        .createOrReplaceTempView("hard_t")

    def exchange_fps(df):
        from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec
        df.collect()
        fps = []

        def walk(n):
            if isinstance(n, TpuShuffleExchangeExec):
                fps.append(n.plan_fingerprint())
            for c in n.children:
                walk(c)
        walk(s.last_plan())
        return fps

    from spark_rapids_tpu.api.functions import col
    t = s.table("hard_t")
    q1 = t.repartition(4, col("k"))
    q2 = t.select(col("k")).repartition(3, col("k"))
    fps1, fps1b, fps2 = (exchange_fps(q1), exchange_fps(q1),
                         exchange_fps(q2))
    assert fps1 and fps1 == fps1b            # deterministic across runs
    assert set(fps1).isdisjoint(fps2)        # structure changes the hash
