"""Two-plane shuffle exchange (docs/shuffle.md): ICI collective routing
under a mesh, DCN fallback, forced planes, the pipelined map-side split's
O(1)-syncs-per-stage property, plane telemetry, and the exchange-plane
plan contract. Runs on the virtual 8-device CPU mesh from conftest.
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.shuffle.exchange import (TpuHashExchangeExec,
                                               TpuShuffleExchangeExec,
                                               plane_totals, shuffle_report)


def _session(**conf):
    return TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE", **conf}).getOrCreate()


def _find(node, klass):
    out = [node] if isinstance(node, klass) else []
    for c in node.children:
        out.extend(_find(c, klass))
    return out


def _df(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({"k": rng.integers(0, 50, n).astype("int64"),
                         "v": rng.normal(0, 1, n)})


def _roundtrip_rows(got, df):
    assert sorted(((int(k), round(float(v), 9)) for k, v in got)) == \
        sorted((int(k), round(float(v), 9)) for k, v in zip(df.k, df.v))


# ---------------------------------------------------------------------------
# Plane routing
# ---------------------------------------------------------------------------

def test_auto_plane_picks_ici_under_mesh():
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "true"})
    df = _df()
    got = s.createDataFrame(df).repartition(4, col("k")).collect()
    _roundtrip_rows(got, df)
    exes = _find(s.last_plan(), TpuShuffleExchangeExec)
    assert exes and all(e.plane_used == "ici" for e in exes), \
        [(type(e).__name__, e.plane, e.plane_used) for e in exes]
    rep = shuffle_report(s.last_plan())
    assert rep and rep[0]["plane"] == "ici"
    assert rep[0]["bytesWritten"] > 0 and rep[0]["bytesRead"] > 0


def test_auto_plane_falls_back_to_dcn_without_mesh():
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "false"})
    df = _df(seed=5)
    got = s.createDataFrame(df).repartition(4, col("k")).collect()
    _roundtrip_rows(got, df)
    exes = _find(s.last_plan(), TpuShuffleExchangeExec)
    assert exes and all(e.plane_used == "dcn" for e in exes)
    assert all(e.mesh is None for e in exes)


def test_forced_dcn_under_mesh_still_correct():
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "true",
                    "spark.rapids.tpu.sql.shuffle.plane": "dcn"})
    df = _df(seed=7)
    got = s.createDataFrame(df).repartition(4, col("k")).collect()
    _roundtrip_rows(got, df)
    exes = _find(s.last_plan(), TpuShuffleExchangeExec)
    assert exes and all(e.plane_used == "dcn" for e in exes)


def test_forced_ici_without_mesh_fails_at_plan_time():
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "false",
                    "spark.rapids.tpu.sql.shuffle.plane": "ici"})
    with pytest.raises(RuntimeError, match="plane=ici"):
        s.createDataFrame(_df()).repartition(4, col("k")).collect()


def test_ici_declines_string_free_schemas_only_when_nested():
    """STRING payloads ride the ICI plane (flat 3-array protocol)."""
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "true"})
    rng = np.random.default_rng(11)
    df = pd.DataFrame({"k": rng.integers(0, 20, 800).astype("int64"),
                       "s": [f"name-{i % 13}" for i in range(800)]})
    got = s.createDataFrame(df).repartition(4, col("k")).collect()
    assert sorted((int(k), v) for k, v in got) == \
        sorted((int(k), v) for k, v in zip(df.k, df.s))
    exes = _find(s.last_plan(), TpuShuffleExchangeExec)
    assert exes and all(e.plane_used == "ici" for e in exes)


# ---------------------------------------------------------------------------
# Multichip shuffle join over ICI exchanges: correct + O(1) syncs/stage
# ---------------------------------------------------------------------------

ICI_JOIN_CONF = {
    "spark.rapids.tpu.sql.mesh.enabled": "true",
    # a tiny maxStageBytes declines the fused TpuMeshJoinExec route, so
    # the planner emits hash exchanges — which the forced plane then
    # routes over collectives: a real shuffled join on the ICI plane
    "spark.rapids.tpu.sql.mesh.maxStageBytes": "1",
    "spark.rapids.tpu.sql.shuffle.plane": "ici",
    "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
}


def test_ici_shuffled_join_correct():
    s = _session(**ICI_JOIN_CONF)
    rng = np.random.default_rng(17)
    left = _df(3000, seed=13)
    right = pd.DataFrame({"b": rng.integers(0, 70, 500).astype("int64"),
                          "y": rng.integers(0, 9, 500).astype("int64")})
    got = (s.createDataFrame(left)
           .join(s.createDataFrame(right), on=(col("k") == col("b")),
                 how="inner").collect())
    exes = _find(s.last_plan(), TpuHashExchangeExec)
    assert len(exes) == 2 and all(e.plane_used == "ici" for e in exes)
    exp = left.merge(right, left_on="k", right_on="b", how="inner")
    got_rows = sorted((int(k), round(float(v), 9), int(b), int(y))
                      for k, v, b, y in got)
    exp_rows = sorted((int(r.k), round(float(r.v), 9), int(r.b), int(r.y))
                      for r in exp.itertuples())
    assert got_rows == exp_rows


def test_q3_shaped_ici_shuffle_join_o1_syncs_per_stage():
    """BASELINE milestone 4 / ISSUE 8 acceptance: a q3-shaped multichip
    3-way shuffle join over the ICI plane pays O(1) host syncs per
    stage — each collective exchange reads back exactly ONE packed
    counts array (span-attributed under shuffle_write), and no sizing
    readback rides the fetch side at all."""
    rng = np.random.default_rng(7)
    n = 8192
    line = pd.DataFrame({
        "l_order": rng.integers(0, 1000, n).astype("int64"),
        "l_price": rng.normal(100.0, 10.0, n)})
    orders = pd.DataFrame({
        "o_key": np.arange(1000, dtype="int64"),
        "o_cust": rng.integers(0, 100, 1000).astype("int64"),
        "o_date": rng.integers(0, 1000, 1000).astype("int64")})
    cust = pd.DataFrame({
        "c_key": np.arange(100, dtype="int64"),
        "c_seg": rng.integers(0, 3, 100).astype("int64")})
    s = _session(**ICI_JOIN_CONF)
    s.createDataFrame(line).createOrReplaceTempView("p_lineitem")
    s.createDataFrame(orders).createOrReplaceTempView("p_orders")
    s.createDataFrame(cust).createOrReplaceTempView("p_customer")
    df = s.sql(
        "SELECT l_price, o_date, c_seg FROM p_lineitem "
        "JOIN p_orders ON l_order = o_key "
        "JOIN p_customer ON o_cust = c_key "
        "WHERE o_date < 700 AND c_seg = 1")
    rows = df.collect()
    exp = (line.merge(orders, left_on="l_order", right_on="o_key")
               .merge(cust, left_on="o_cust", right_on="c_key"))
    exp = exp[(exp.o_date < 700) & (exp.c_seg == 1)]
    assert len(rows) == len(exp)
    exes = _find(s.last_plan(), TpuShuffleExchangeExec)
    assert len(exes) == 4 and all(e.plane_used == "ici" for e in exes)
    sync = s.last_query_metrics()["sync"]
    # each ICI exchange = ONE counts readback inside its shuffle_write
    # span; 4 exchanges -> at most 4 write-side syncs for the whole query
    assert sync["syncSpans"].get("shuffle_write", 0) <= len(exes), sync
    # and the fetch side (run slicing) never syncs
    assert sync["syncSpans"].get("shuffle_fetch", 0) == 0, sync


# ---------------------------------------------------------------------------
# DCN plane: the pipelined map-side split packs its sizing readbacks
# ---------------------------------------------------------------------------

def _dcn_join_syncs(depth: int):
    rng = np.random.default_rng(7)
    n = 16384
    line = pd.DataFrame({"l_order": rng.integers(0, 1000, n).astype("int64"),
                         "l_price": rng.normal(100.0, 10.0, n)})
    orders = pd.DataFrame({"o_key": np.arange(1000, dtype="int64"),
                           "o_cust": rng.integers(0, 100, 1000).astype("int64")})
    s = _session(**{
        "spark.rapids.tpu.sql.mesh.enabled": "false",
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.tpu.sql.shuffle.pipelineDepth": str(depth),
        "spark.rapids.tpu.sql.reader.batchSizeRows": "1024"})
    got = (s.createDataFrame(line)
           .join(s.createDataFrame(orders),
                 on=(col("l_order") == col("o_key")), how="inner").collect())
    assert len(got) == n
    sync = s.last_query_metrics()["sync"]
    return sync["syncSpans"].get("pipeline_resolve", 0), sync


def test_dcn_map_split_sizing_packs_into_o1_resolves():
    """The 16-batch stream exchange must NOT pay one sizing readback per
    batch: with the split window deep enough, the whole map phase packs
    into a handful of batched resolves — strictly fewer than the batch
    count, and strictly fewer than the depth-1 (read-per-batch) run of
    the identical query."""
    stream_batches = 16
    packed, sync = _dcn_join_syncs(depth=32)
    assert packed < stream_batches, sync
    per_batch, _ = _dcn_join_syncs(depth=1)
    assert packed < per_batch, (packed, per_batch)
    # every counted sync is span-attributed (no unattributed leaks)
    assert sum(sync["syncSpans"].values()) == sync["hostSyncs"]


# ---------------------------------------------------------------------------
# Telemetry + contract
# ---------------------------------------------------------------------------

def test_plane_totals_and_telemetry_gauges():
    before = plane_totals()
    s = _session(**{"spark.rapids.tpu.sql.mesh.enabled": "true"})
    df = _df(seed=23)
    s.createDataFrame(df).repartition(4, col("k")).collect()
    after = plane_totals()
    assert after["ici_exchanges"] > before["ici_exchanges"]
    assert after["ici_bytes"] > before["ici_bytes"]
    assert after["ici_seconds"] > before["ici_seconds"]
    from spark_rapids_tpu.service.telemetry import (MetricsRegistry,
                                                    compact_snapshot)
    snap = MetricsRegistry.get().collect()
    fam = snap.get("tpu_shuffle_exchanges_total")
    assert fam is not None
    planes = {dict(s0["labels"]).get("plane"): s0["value"]
              for s0 in fam["samples"]}
    assert planes.get("ici", 0) >= after["ici_exchanges"] - 1
    compact = compact_snapshot()
    assert "shufflePlanes" in compact and "ici" in compact["shufflePlanes"]
    assert compact["shufflePlanes"]["ici"]["exchanges"] >= 1


def test_exchange_plane_contract_flags_forced_ici_without_mesh():
    """The plan-contract validator knows the exchange's plane shape: a
    plane forced to ici with no mesh attached is a structural violation
    (validate_plan), independent of the plan-time RuntimeError."""
    from spark_rapids_tpu.analysis.contracts import validate_plan
    from spark_rapids_tpu.plan.physical import TpuLocalScanExec
    from spark_rapids_tpu.ops.expressions import ColumnRef
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    b = ColumnarBatch.from_pydict({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
    scan = TpuLocalScanExec(b.to_arrow(), b.schema)
    ex = TpuShuffleExchangeExec(scan, 4, [ColumnRef("k").resolve(b.schema)],
                                plane="ici", mesh=None)
    violations = validate_plan(ex)
    assert any("ici" in v.message and "mesh" in v.message
               for v in violations), violations
    # a well-formed auto exchange is clean
    ok = TpuShuffleExchangeExec(scan, 4, [ColumnRef("k").resolve(b.schema)])
    assert not [v for v in validate_plan(ok)
                if "plane" in v.message or "mesh" in v.message]
