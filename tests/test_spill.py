"""Spill store tests: tier transitions, budgets, priorities, rematerialization.

Reference analog: RapidsDeviceMemoryStoreSuite / RapidsHostMemoryStoreSuite /
RapidsDiskStoreSuite / RapidsBufferCatalogSuite / SpillableColumnarBatchSuite
(SURVEY.md §4 ring 1).
"""

import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.spill import (ACTIVE_ON_DECK_PRIORITY,
                                         OUTPUT_FOR_SHUFFLE_PRIORITY,
                                         BufferCatalog, SpillableColumnarBatch,
                                         StorageTier)


@pytest.fixture
def catalog(tmp_path):
    return BufferCatalog(device_budget=1 << 20, host_budget=1 << 20,
                         spill_dir=str(tmp_path))


def _batch(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return ColumnarBatch.from_pydict({
        "a": rng.integers(0, 1000, n),
        "b": rng.normal(size=n),
        "s": [f"row-{i}" for i in range(n)],
    })


def test_register_and_acquire_roundtrip(catalog):
    b = _batch()
    bid = catalog.register_batch(b)
    out = catalog.acquire_batch(bid)
    assert out.to_pydict() == b.to_pydict()


def test_spill_to_host_and_back(catalog):
    b = _batch()
    bid = catalog.register_batch(b)
    buf = catalog.buffers[bid]
    moved = buf.spill_to_host()
    assert moved > 0
    assert buf.tier == StorageTier.HOST
    assert catalog.acquire_batch(bid).to_pydict() == b.to_pydict()


def test_spill_to_disk_and_back(catalog, tmp_path):
    b = _batch()
    bid = catalog.register_batch(b)
    buf = catalog.buffers[bid]
    buf.spill_to_disk(str(tmp_path))
    assert buf.tier == StorageTier.DISK
    assert catalog.acquire_batch(bid).to_pydict() == b.to_pydict()


def test_budget_triggers_spill(tmp_path):
    one = _batch(1000).device_size_bytes()
    cat = BufferCatalog(device_budget=3 * one, host_budget=10 << 20,
                        spill_dir=str(tmp_path))
    ids = [cat.register_batch(_batch(1000, seed=i)) for i in range(5)]
    assert cat.device_bytes <= 3 * one
    assert any(cat.buffers[i].tier != StorageTier.DEVICE for i in ids)
    # all batches still readable
    for i in ids:
        assert cat.acquire_batch(i).num_rows == 1000


def test_priority_order_spills_shuffle_first(tmp_path):
    cat = BufferCatalog(device_budget=10 << 20, host_budget=10 << 20,
                        spill_dir=str(tmp_path))
    shuffle_id = cat.register_batch(_batch(500, 1), OUTPUT_FOR_SHUFFLE_PRIORITY)
    active_id = cat.register_batch(_batch(500, 2), ACTIVE_ON_DECK_PRIORITY)
    with cat._mu:
        cat._spill_device_to_locked(cat.device_bytes - 1)  # force one spill
    assert cat.buffers[shuffle_id].tier == StorageTier.HOST
    assert cat.buffers[active_id].tier == StorageTier.DEVICE


def test_host_budget_cascades_to_disk(tmp_path):
    one = _batch(1000).device_size_bytes()
    cat = BufferCatalog(device_budget=2 * one, host_budget=2 * one,
                        spill_dir=str(tmp_path))
    ids = [cat.register_batch(_batch(1000, seed=i)) for i in range(6)]
    tiers = {cat.buffers[i].tier for i in ids}
    assert StorageTier.DISK in tiers
    for i in ids:
        assert cat.acquire_batch(i).num_rows == 1000


def test_reserve_spills_ahead(tmp_path):
    one = _batch(1000).device_size_bytes()
    cat = BufferCatalog(device_budget=3 * one, host_budget=10 << 20,
                        spill_dir=str(tmp_path))
    cat.register_batch(_batch(1000, 1), OUTPUT_FOR_SHUFFLE_PRIORITY)
    used = cat.device_bytes
    cat.reserve(3 * one - used // 2)  # needs more than remaining
    assert cat.device_bytes <= used // 2 + 1


def test_spillable_batch_close_frees(catalog):
    b = _batch()
    with SpillableColumnarBatch(b, catalog=catalog) as sb:
        assert sb.get_batch().num_rows == 100
        bid = sb._id
        assert bid in catalog.buffers
    assert bid not in catalog.buffers


def test_remove_deletes_disk_file(catalog, tmp_path):
    b = _batch()
    bid = catalog.register_batch(b)
    catalog.buffers[bid].spill_to_disk(str(tmp_path))
    path = catalog.buffers[bid]._disk_path
    import os
    assert os.path.exists(path)
    catalog.remove(bid)
    assert not os.path.exists(path)


def test_spill_to_disk_write_outside_lock_race_safe(catalog, tmp_path):
    """The npz disk write happens OUTSIDE the buffer RLock (snapshot
    under the lock, write unlocked, re-take to flip the tier), so a
    concurrent promotion can interleave with an in-flight spill. Hammer
    spill_to_disk against acquire_batch: whatever interleaving wins, the
    data survives intact, a lost race leaves no orphan npz behind, and
    the loser reports 0 bytes moved."""
    import glob
    import os
    import threading

    b = _batch(200)
    bid = catalog.register_batch(b)
    buf = catalog.buffers[bid]
    errors = []
    start = threading.Barrier(2)

    def spiller():
        try:
            start.wait()
            for _ in range(10):
                moved = buf.spill_to_disk(str(tmp_path))
                assert moved >= 0
        except Exception as e:          # pragma: no cover - failure path
            errors.append(e)

    def promoter():
        try:
            start.wait()
            for _ in range(10):
                out = catalog.acquire_batch(bid)
                assert out.num_rows == 200
        except Exception as e:          # pragma: no cover - failure path
            errors.append(e)

    ts = [threading.Thread(target=spiller),
          threading.Thread(target=promoter)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errors, errors
    # data still correct whatever tier it landed on
    assert catalog.acquire_batch(bid).to_pydict() == b.to_pydict()
    # after remove, no npz file may survive: a spill that lost its race
    # must have unlinked its own (per-attempt unique) file
    catalog.remove(bid)
    assert glob.glob(os.path.join(str(tmp_path), "spill-*.npz")) == []


def test_semaphore():
    from spark_rapids_tpu.exec.device import TpuSemaphore
    sem = TpuSemaphore(2)
    sem.acquire_if_necessary()
    sem.acquire_if_necessary()  # idempotent same-thread
    assert sem._sem._value == 1
    sem.release_if_necessary()
    sem.release_if_necessary()
    assert sem._sem._value == 2
