"""SQL front end tests (the reference's entire entry point is SQL text
through Catalyst — SQLExecPlugin, sql-plugin/.../Plugin.scala:40-59; here
session.sql() parses a minimal dialect onto the same logical plans the
DataFrame API builds, so every query below runs the planner-driven TPU
path and is golden-checked against expected rows)."""
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.sql import SqlParseError


@pytest.fixture()
def session():
    s = TpuSession.builder.getOrCreate()
    s.createDataFrame({
        "k": [1, 2, 1, 3], "v": [10.0, 20.0, 30.0, 40.0],
        "name": ["aa", "bb", "ab", "cc"],
    }).createOrReplaceTempView("t")
    s.createDataFrame({
        "k": [1, 2, 3], "label": ["x", "y", "z"],
    }).createOrReplaceTempView("dim")
    return s


def test_sql_select_star(session):
    assert session.sql("SELECT * FROM t").collect() == [
        (1, 10.0, "aa"), (2, 20.0, "bb"), (1, 30.0, "ab"), (3, 40.0, "cc")]


def test_sql_project_filter(session):
    out = session.sql(
        "SELECT k, v * 2 AS dv FROM t WHERE v > 15").collect()
    assert out == [(2, 40.0), (1, 60.0), (3, 80.0)]


def test_sql_group_by_order_by(session):
    out = session.sql(
        "SELECT k, sum(v) AS sv, count(*) AS c FROM t "
        "GROUP BY k ORDER BY sv DESC, k").collect()
    assert out == [(1, 40.0, 2), (3, 40.0, 1), (2, 20.0, 1)]


def test_sql_join_on(session):
    out = session.sql(
        "SELECT t.k, label, v FROM t JOIN dim ON t.k = dim.k "
        "WHERE name LIKE 'a%'").collect()
    assert sorted(out) == [(1, "x", 10.0), (1, "x", 30.0)]


def test_sql_join_using(session):
    out = session.sql(
        "SELECT k, label, v FROM t LEFT JOIN dim USING (k) "
        "ORDER BY v").collect()
    assert out == [(1, "x", 10.0), (2, "y", 20.0), (1, "x", 30.0),
                   (3, "z", 40.0)]


def test_sql_having(session):
    out = session.sql(
        "SELECT k, sum(v) AS sv FROM t GROUP BY k "
        "HAVING sum(v) > 25 ORDER BY k").collect()
    assert out == [(1, 40.0), (3, 40.0)]


def test_sql_case_when_group_by_position(session):
    out = session.sql(
        "SELECT CASE WHEN v > 25 THEN 'hi' ELSE 'lo' END AS b, "
        "count(*) AS c FROM t GROUP BY 1 ORDER BY b").collect()
    assert out == [("hi", 2), ("lo", 2)]


def test_sql_count_distinct(session):
    assert session.sql(
        "SELECT count(DISTINCT k) AS dk FROM t").collect() == [(3,)]


def test_sql_limit_and_functions(session):
    out = session.sql(
        "SELECT upper(name) AS u FROM t ORDER BY u LIMIT 2").collect()
    assert out == [("AA",), ("AB",)]


def test_sql_subquery_in_from(session):
    out = session.sql(
        "SELECT avg(v) AS a FROM (SELECT v FROM t WHERE k = 1) sub"
    ).collect()
    assert out == [(20.0,)]


def test_sql_between_in(session):
    out = session.sql(
        "SELECT k, v FROM t WHERE v BETWEEN 15 AND 35 AND k IN (1, 2)"
    ).collect()
    assert out == [(2, 20.0), (1, 30.0)]


def test_sql_group_by_expression_restated(session):
    out = session.sql(
        "SELECT substring(name, 1, 1) AS c1, count(*) AS n FROM t "
        "GROUP BY substring(name, 1, 1) ORDER BY c1").collect()
    assert out == [("a", 2), ("b", 1), ("c", 1)]


def test_sql_distinct(session):
    assert session.sql(
        "SELECT DISTINCT k FROM t ORDER BY k").collect() == [(1,), (2,), (3,)]


def test_sql_matches_dataframe_api(session):
    """Dual-path golden: the SQL text and the DataFrame calls build the
    same answer (SparkQueryCompareTestSuite's dual-session idiom)."""
    sql_out = session.sql(
        "SELECT k, sum(v) AS sv FROM t WHERE v > 5 GROUP BY k "
        "ORDER BY k").collect()
    df_out = (session.table("t").filter(col("v") > 5).groupBy("k")
              .agg(F.sum("v").alias("sv")).orderBy("k").collect())
    assert sql_out == df_out


def test_sql_runs_on_tpu(session):
    session.sql("SELECT k, sum(v) AS sv FROM t GROUP BY k").collect()
    session.assert_on_tpu()


def test_sql_date_and_interval(session):
    s = session
    s.createDataFrame({"d": ["2024-01-10", "2024-03-05"]}) \
        .select(col("d").cast("date").alias("d")) \
        .createOrReplaceTempView("dates")
    out = s.sql("SELECT count(*) AS c FROM dates "
                "WHERE d >= DATE '2024-01-01' "
                "AND d < DATE '2024-01-01' + INTERVAL '2' MONTH").collect()
    assert out == [(1,)]


def test_sql_error_cases(session):
    with pytest.raises(SqlParseError):
        # comma join + qualified refs over a shared column name: the
        # single-namespace resolver would silently cross-product, so it
        # must refuse instead
        session.sql("SELECT label, v FROM t, dim WHERE t.k = dim.k")
    with pytest.raises(SqlParseError):
        session.sql("SELECT FROM t")
    with pytest.raises(SqlParseError):
        session.sql("SELECT * FROM missing_table")
    with pytest.raises(SqlParseError):
        session.sql("DELETE FROM t")
    with pytest.raises(SqlParseError):
        session.sql("SELECT k FROM t; DROP TABLE t")


def test_dataframe_computed_grouping_key(session):
    """Regression: computed (non-ColumnRef) grouping keys must survive
    analysis (identity link between grouping and output lists)."""
    df = session.table("t")
    b = F.when(col("v") > 25, "hi").otherwise("lo").alias("b")
    out = df.groupBy(b).agg(F.count("*").alias("c")).collect()
    assert sorted(out) == [("hi", 2), ("lo", 2)]


def test_sql_negative_in_list_and_regexp(session):
    out = session.sql(
        "SELECT k FROM t WHERE k - 2 IN (-1, 0) ORDER BY k").collect()
    assert out == [(1,), (1,), (2,)]
    out = session.sql(
        "SELECT regexp_replace(name, 'a+', 'X') AS r FROM t ORDER BY r"
    ).collect()
    assert out == [("X",), ("Xb",), ("bb",), ("cc",)]


def test_sql_tpch_q6_text():
    """TPC-H q6 as SQL TEXT through session.sql, golden against the
    DataFrame-API build of the same query."""
    from spark_rapids_tpu.api.session import TpuSession
    from benchmarks import datagen, queries as Q

    s = TpuSession.builder.getOrCreate()
    tables = datagen.register_tables(s, 0.002)
    sql_out = s.sql(
        "SELECT sum(l_extendedprice * l_discount) AS revenue "
        "FROM lineitem "
        "WHERE l_shipdate >= DATE '1994-01-01' "
        "AND l_shipdate < DATE '1995-01-01' "
        "AND l_discount BETWEEN 0.05 AND 0.07 "
        "AND l_quantity < 24").collect()
    api_out = Q.QUERIES["q6"](tables).collect()
    assert abs(sql_out[0][0] - api_out[0][0]) < 1e-6


def test_sql_tpch_q1_text():
    from spark_rapids_tpu.api.session import TpuSession
    from benchmarks import datagen, queries as Q

    s = TpuSession.builder.getOrCreate()
    tables = datagen.register_tables(s, 0.002)
    sql_out = s.sql(
        "SELECT l_returnflag, l_linestatus, "
        "sum(l_quantity) AS sum_qty, "
        "sum(l_extendedprice) AS sum_base_price, "
        "sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price, "
        "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge, "
        "avg(l_quantity) AS avg_qty, avg(l_extendedprice) AS avg_price, "
        "avg(l_discount) AS avg_disc, count(*) AS count_order "
        "FROM lineitem WHERE l_shipdate <= DATE '1998-09-02' "
        "GROUP BY l_returnflag, l_linestatus "
        "ORDER BY l_returnflag, l_linestatus").collect()
    api_out = Q.QUERIES["q1"](tables).collect()
    assert len(sql_out) == len(api_out)
    for a, b in zip(sql_out, api_out):
        assert a[0] == b[0] and a[1] == b[1]
        for x, y in zip(a[2:], b[2:]):
            assert abs(x - y) <= 1e-6 * max(1.0, abs(y)), (a, b)


def test_sql_tpch_q3_text():
    from spark_rapids_tpu.api.session import TpuSession
    from benchmarks import datagen, queries as Q

    s = TpuSession.builder.getOrCreate()
    tables = datagen.register_tables(s, 0.002)
    sql_out = s.sql(
        "SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS "
        "revenue, o_orderdate, o_shippriority "
        "FROM customer JOIN orders ON c_custkey = o_custkey "
        "JOIN lineitem ON o_orderkey = l_orderkey "
        "WHERE c_mktsegment = 'BUILDING' "
        "AND o_orderdate < DATE '1995-03-15' "
        "AND l_shipdate > DATE '1995-03-15' "
        "GROUP BY l_orderkey, o_orderdate, o_shippriority "
        "ORDER BY revenue DESC, o_orderdate LIMIT 10").collect()
    api_out = Q.QUERIES["q3"](tables).collect()
    assert len(sql_out) == len(api_out)
    # SQL selects revenue second; the API groups-first form puts it last
    for a, b in zip(sql_out, api_out):
        assert a[0] == b[0] and abs(a[1] - b[3]) < 1e-6 and \
            a[2] == b[1] and a[3] == b[2]


def test_sql_not_in_subquery_null_aware(session):
    """NOT IN (SELECT ...) follows SQL three-valued semantics (Spark's
    null-aware anti join): any NULL in the subquery output empties the
    result; NULL probe values never qualify; an EMPTY subquery keeps
    everything."""
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    s.createDataFrame({"k": [1, 2, 3, None]}).createOrReplaceTempView("ti")
    s.createDataFrame({"fk": [1, None]}).createOrReplaceTempView("tu_null")
    s.createDataFrame({"fk": [1]}).createOrReplaceTempView("tu_plain")
    s.createDataFrame({"fk": [9]}).createOrReplaceTempView("tu_nine")
    # NULL in subquery -> nothing qualifies
    assert s.sql("SELECT k FROM ti WHERE k NOT IN "
                 "(SELECT fk FROM tu_null)").collect() == []
    # no NULLs: plain anti semantics, NULL probe row excluded
    assert sorted(s.sql(
        "SELECT k FROM ti WHERE k NOT IN (SELECT fk FROM tu_plain)"
    ).collect()) == [(2,), (3,)]
    # empty subquery -> every row qualifies (even the NULL probe)
    out = s.sql("SELECT k FROM ti WHERE k NOT IN "
                "(SELECT fk FROM tu_nine WHERE fk < 0)").collect()
    assert len(out) == 4


def test_sql_correlated_count_scalar_empty_group(session):
    """A correlated scalar COUNT over an empty group is 0, not NULL
    (RewriteCorrelatedScalarSubquery's count default): rows whose group
    is empty must still satisfy '= 0'."""
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    s.createDataFrame({"k": [1, 2, 3]}).createOrReplaceTempView("co_t")
    s.createDataFrame({"fk": [1, 1, 3]}).createOrReplaceTempView("co_u")
    out = sorted(s.sql(
        "SELECT k FROM co_t WHERE "
        "(SELECT count(*) FROM co_u WHERE fk = k) = 0").collect())
    assert out == [(2,)], out
    out = sorted(s.sql(
        "SELECT k FROM co_t WHERE "
        "(SELECT count(*) FROM co_u WHERE fk = k) = 2").collect())
    assert out == [(1,)], out


def test_sql_select_star_no_subquery_column_leak():
    """SELECT * must expand from the pre-rewrite column list: correlated
    scalar-subquery decorrelation LEFT-joins a hidden __sqN_val column
    onto the frame, which leaked into the star projection (ADVICE r5 —
    silent wrong output)."""
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    s.createDataFrame({"k": [1, 2, 3], "v": [10.0, 20.0, 30.0]}
                      ).createOrReplaceTempView("sl_t")
    s.createDataFrame({"fk": [1, 1, 2], "w": [4.0, 6.0, 100.0]}
                      ).createOrReplaceTempView("sl_u")
    df = s.sql("SELECT * FROM sl_t WHERE "
               "v > (SELECT avg(w) FROM sl_u WHERE fk = k)")
    assert df.columns == ["k", "v"], df.columns
    assert sorted(df.collect()) == [(1, 10.0)]
    # star + extra expression: same pre-rewrite expansion
    df2 = s.sql("SELECT *, v + 1 AS v1 FROM sl_t WHERE "
                "v > (SELECT avg(w) FROM sl_u WHERE fk = k)")
    assert df2.columns == ["k", "v", "v1"], df2.columns
    assert sorted(df2.collect()) == [(1, 10.0, 11.0)]
