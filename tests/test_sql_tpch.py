"""All 22 TPC-H queries as SQL TEXT through session.sql(), asserted
row-equal to the DataFrame-API builds of the same queries
(benchmarks/queries.py) at tiny scale.

This is the reference's front door — arbitrary SQL through Catalyst
(Plugin.scala:40-59) — exercised end-to-end: the texts use the standard
TPC-H shapes including WHERE-clause subqueries (EXISTS/NOT EXISTS, [NOT]
IN (SELECT ...), correlated and uncorrelated scalars), WITH views, and
derived tables, adapted only where the data generator's schema differs
(the same adaptations the DataFrame builds document)."""

import pytest

from benchmarks import datagen, queries as Q


_SF = 0.002

# date literals used by the builds (days since epoch -> ISO)
# 8766=1994-01-01  8857=+91d  9131=1995-01-01  9204=1995-03-15
# 9374=1995-09-01  9404=+30d  9861=1996-12-31  8856=+90d

TPCH_SQL = {
    "q1": """
SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       avg(l_quantity) AS avg_qty, avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc, count(*) AS count_order
FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus""",

    "q2": """
SELECT s_acctbal, s_name, n_name, p_partkey, p_type
FROM part
JOIN partsupp ON p_partkey = ps_partkey
JOIN supplier ON s_suppkey = ps_suppkey
JOIN nation ON s_nationkey = n_nationkey
JOIN region ON n_regionkey = r_regionkey
WHERE p_size = 15 AND p_type LIKE '%BRASS' AND r_name = 'EUROPE'
  AND ps_supplycost = (
    SELECT min(ps_supplycost)
    FROM partsupp JOIN supplier ON s_suppkey = ps_suppkey
    JOIN nation ON s_nationkey = n_nationkey
    JOIN region ON n_regionkey = r_regionkey
    WHERE p_partkey = ps_partkey AND r_name = 'EUROPE')
ORDER BY s_acctbal DESC, n_name, s_name, p_partkey
LIMIT 100""",

    "q3": """
SELECT l_orderkey, o_orderdate, o_shippriority,
       sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON o_orderkey = l_orderkey
WHERE c_mktsegment = 'BUILDING' AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10""",

    "q4": """
SELECT o_orderpriority, count(*) AS order_count
FROM orders
WHERE o_orderdate >= DATE '1994-01-01' AND o_orderdate < DATE '1994-04-02'
  AND EXISTS (SELECT * FROM lineitem
              WHERE l_orderkey = o_orderkey
                AND l_commitdate < l_receiptdate)
GROUP BY o_orderpriority
ORDER BY o_orderpriority""",

    "q5": """
SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON o_orderkey = l_orderkey
JOIN supplier ON l_suppkey = s_suppkey AND c_nationkey = s_nationkey
JOIN nation ON s_nationkey = n_nationkey
JOIN region ON n_regionkey = r_regionkey
WHERE r_name = 'ASIA' AND o_orderdate >= DATE '1994-01-01'
  AND o_orderdate < DATE '1995-01-01'
GROUP BY n_name
ORDER BY revenue DESC""",

    "q6": """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01' AND l_shipdate < DATE '1995-01-01'
  AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24""",

    "q7": """
SELECT supp_nation, cust_nation, l_year, sum(volume) AS revenue
FROM (SELECT supp_nation, cust_nation, year(l_shipdate) AS l_year,
             l_extendedprice * (1 - l_discount) AS volume
      FROM lineitem
      JOIN supplier ON l_suppkey = s_suppkey
      JOIN (SELECT n_nationkey AS supp_nationkey, n_name AS supp_nation
            FROM nation WHERE n_name IN ('FRANCE', 'GERMANY')) sn
        ON s_nationkey = supp_nationkey
      JOIN orders ON l_orderkey = o_orderkey
      JOIN customer ON o_custkey = c_custkey
      JOIN (SELECT n_nationkey AS cust_nationkey, n_name AS cust_nation
            FROM nation WHERE n_name IN ('FRANCE', 'GERMANY')) cn
        ON c_nationkey = cust_nationkey
      WHERE l_shipdate >= DATE '1995-01-01'
        AND l_shipdate <= DATE '1996-12-31'
        AND ((supp_nation = 'FRANCE' AND cust_nation = 'GERMANY') OR
             (supp_nation = 'GERMANY' AND cust_nation = 'FRANCE'))) shipping
GROUP BY supp_nation, cust_nation, l_year
ORDER BY supp_nation, cust_nation, l_year""",

    "q8": """
SELECT o_year, sum(CASE WHEN supp_nation = 'BRAZIL' THEN volume
                        ELSE 0.0 END) / sum(volume) AS mkt_share
FROM (SELECT year(o_orderdate) AS o_year,
             l_extendedprice * (1 - l_discount) AS volume, supp_nation
      FROM lineitem
      JOIN part ON l_partkey = p_partkey
      JOIN supplier ON l_suppkey = s_suppkey
      JOIN orders ON l_orderkey = o_orderkey
      JOIN customer ON o_custkey = c_custkey
      JOIN (SELECT n_nationkey AS cust_nationkey, n_regionkey
            FROM nation) cn ON c_nationkey = cust_nationkey
      JOIN region ON n_regionkey = r_regionkey
      JOIN (SELECT n_nationkey AS supp_nationkey, n_name AS supp_nation
            FROM nation) sn ON s_nationkey = supp_nationkey
      WHERE r_name = 'AMERICA' AND p_type = 'ECONOMY ANODIZED STEEL'
        AND o_orderdate >= DATE '1995-01-01'
        AND o_orderdate <= DATE '1996-12-31') all_nations
GROUP BY o_year
ORDER BY o_year""",

    "q9": """
SELECT n_name, o_year, sum(amount) AS sum_profit
FROM (SELECT n_name, year(o_orderdate) AS o_year,
             l_extendedprice * (1 - l_discount) -
             ps_supplycost * l_quantity AS amount
      FROM lineitem
      JOIN part ON l_partkey = p_partkey
      JOIN supplier ON l_suppkey = s_suppkey
      JOIN partsupp ON l_partkey = ps_partkey AND l_suppkey = ps_suppkey
      JOIN orders ON l_orderkey = o_orderkey
      JOIN nation ON s_nationkey = n_nationkey
      WHERE p_type LIKE '%BRUSHED%') profit
GROUP BY n_name, o_year
ORDER BY n_name, o_year DESC""",

    "q10": """
SELECT c_custkey, c_name, c_acctbal, n_name,
       sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON o_orderkey = l_orderkey
JOIN nation ON c_nationkey = n_nationkey
WHERE o_orderdate >= DATE '1994-01-01'
  AND o_orderdate < DATE '1994-04-02' AND l_returnflag = 'R'
GROUP BY c_custkey, c_name, c_acctbal, n_name
ORDER BY revenue DESC, c_custkey
LIMIT 20""",

    "q11": """
SELECT ps_partkey, sum(ps_supplycost * ps_availqty) AS value
FROM partsupp
JOIN supplier ON ps_suppkey = s_suppkey
JOIN nation ON s_nationkey = n_nationkey
WHERE n_name = 'GERMANY'
GROUP BY ps_partkey
HAVING sum(ps_supplycost * ps_availqty) > (
  SELECT sum(ps_supplycost * ps_availqty) * 0.0001
  FROM partsupp
  JOIN supplier ON ps_suppkey = s_suppkey
  JOIN nation ON s_nationkey = n_nationkey
  WHERE n_name = 'GERMANY')
ORDER BY value DESC, ps_partkey""",

    "q12": """
SELECT l_shipmode,
       sum(CASE WHEN o_orderpriority IN ('1-URGENT', '2-HIGH') THEN 1
                ELSE 0 END) AS high_line_count,
       sum(CASE WHEN o_orderpriority NOT IN ('1-URGENT', '2-HIGH') THEN 1
                ELSE 0 END) AS low_line_count
FROM orders JOIN lineitem ON o_orderkey = l_orderkey
WHERE l_shipmode IN ('MAIL', 'SHIP') AND l_commitdate < l_receiptdate
  AND l_shipdate < l_commitdate AND l_receiptdate >= DATE '1994-01-01'
  AND l_receiptdate < DATE '1995-01-01'
GROUP BY l_shipmode
ORDER BY l_shipmode""",

    "q13": """
SELECT c_count, count(*) AS custdist
FROM (SELECT c_custkey, count(o_orderkey) AS c_count
      FROM customer
      LEFT JOIN (SELECT * FROM orders
                 WHERE NOT (o_comment LIKE '%special%'
                            AND o_comment LIKE '%requests%')) o
        ON c_custkey = o_custkey
      GROUP BY c_custkey) c_orders
GROUP BY c_count
ORDER BY custdist DESC, c_count DESC""",

    "q14": """
SELECT 100.0 * sum(CASE WHEN p_type LIKE 'PROMO%'
                        THEN l_extendedprice * (1 - l_discount)
                        ELSE 0.0 END) /
       sum(l_extendedprice * (1 - l_discount)) AS promo_revenue
FROM lineitem JOIN part ON l_partkey = p_partkey
WHERE l_shipdate >= DATE '1995-09-01' AND l_shipdate < DATE '1995-10-01'""",

    "q15": """
WITH revenue AS (
  SELECT l_suppkey AS supplier_no,
         sum(l_extendedprice * (1 - l_discount)) AS total_revenue
  FROM lineitem
  WHERE l_shipdate >= DATE '1994-01-01' AND l_shipdate < DATE '1994-04-01'
  GROUP BY l_suppkey)
SELECT s_suppkey, s_name, total_revenue
FROM supplier JOIN revenue ON s_suppkey = supplier_no
WHERE total_revenue >= (SELECT max(total_revenue) FROM revenue) * 0.999999
ORDER BY s_suppkey""",

    "q16": """
SELECT p_brand, p_type, p_size, count(DISTINCT ps_suppkey) AS supplier_cnt
FROM partsupp JOIN part ON ps_partkey = p_partkey
WHERE p_brand <> 'Brand#45' AND p_type NOT LIKE 'MEDIUM POLISHED%'
  AND p_size IN (49, 14, 23, 45, 19, 3, 36, 9)
  AND ps_suppkey NOT IN (SELECT s_suppkey FROM supplier
                         WHERE s_acctbal < 0)
GROUP BY p_brand, p_type, p_size
ORDER BY supplier_cnt DESC, p_brand, p_type, p_size""",

    "q17": """
SELECT sum(l_extendedprice) / 7.0 AS avg_yearly
FROM lineitem JOIN part ON p_partkey = l_partkey
WHERE p_brand = 'Brand#23' AND p_container = 'MED BOX'
  AND l_quantity < (SELECT 0.2 * avg(l_quantity) FROM lineitem
                    WHERE l_partkey = p_partkey)""",

    "q18": """
SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
       sum(l_quantity) AS sum_qty
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON o_orderkey = l_orderkey
WHERE o_orderkey IN (SELECT l_orderkey FROM lineitem
                     GROUP BY l_orderkey HAVING sum(l_quantity) > 120)
GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
ORDER BY o_totalprice DESC, o_orderdate
LIMIT 100""",

    "q19": """
SELECT sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM lineitem JOIN part ON p_partkey = l_partkey
WHERE l_shipmode IN ('AIR', 'REG AIR')
  AND ((p_brand = 'Brand#12' AND p_container IN ('SM CASE', 'SM BOX')
        AND l_quantity >= 1 AND l_quantity <= 11 AND p_size <= 5) OR
       (p_brand = 'Brand#23' AND p_container IN ('MED BAG', 'MED BOX')
        AND l_quantity >= 10 AND l_quantity <= 20 AND p_size <= 10) OR
       (p_brand = 'Brand#34' AND p_container IN ('LG CASE', 'LG BOX')
        AND l_quantity >= 20 AND l_quantity <= 30 AND p_size <= 15))""",

    "q20": """
SELECT s_name
FROM supplier
JOIN nation ON s_nationkey = n_nationkey
WHERE n_name = 'CANADA'
  AND s_suppkey IN (
    SELECT ps_suppkey FROM partsupp
    WHERE ps_partkey IN (SELECT p_partkey FROM part
                         WHERE p_type LIKE '%TIN%')
      AND ps_availqty > (
        SELECT 0.5 * sum(l_quantity) FROM lineitem
        WHERE l_partkey = ps_partkey AND l_suppkey = ps_suppkey
          AND l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1995-01-01'))
ORDER BY s_name""",

    "q21": """
SELECT s_name, count(*) AS numwait
FROM supplier
JOIN lineitem l1 ON s_suppkey = l_suppkey
JOIN orders ON o_orderkey = l_orderkey
JOIN nation ON s_nationkey = n_nationkey
WHERE o_orderstatus = 'F' AND l_receiptdate > l_commitdate
  AND n_name = 'FRANCE'
  AND EXISTS (SELECT * FROM lineitem l2
              WHERE l2.l_orderkey = l1.l_orderkey
                AND l2.l_suppkey <> l1.l_suppkey)
  AND NOT EXISTS (SELECT * FROM lineitem l3
                  WHERE l3.l_orderkey = l1.l_orderkey
                    AND l3.l_suppkey <> l1.l_suppkey
                    AND l3.l_receiptdate > l3.l_commitdate)
GROUP BY s_name
ORDER BY numwait DESC, s_name
LIMIT 100""",

    "q22": """
SELECT cntrycode, count(*) AS numcust, sum(c_acctbal) AS totacctbal
FROM (SELECT substring(c_phone, 1, 2) AS cntrycode, c_acctbal, c_custkey
      FROM customer
      WHERE substring(c_phone, 1, 2) IN
            ('13', '31', '23', '29', '30', '18', '17')
        AND c_acctbal > (
          SELECT avg(c_acctbal) FROM customer
          WHERE c_acctbal > 0.0
            AND substring(c_phone, 1, 2) IN
                ('13', '31', '23', '29', '30', '18', '17'))) custsale
WHERE NOT EXISTS (SELECT * FROM orders WHERE o_custkey = c_custkey)
GROUP BY cntrycode
ORDER BY cntrycode""",
}


def _cmp_rows(sql_rows, api_rows, qname, tol=5e-5):
    assert len(sql_rows) == len(api_rows), \
        (qname, len(sql_rows), len(api_rows))
    import math

    def key(r):
        return tuple(repr(type(v)) + (f"{v:.4f}" if isinstance(v, float)
                                      else repr(v)) for v in r)
    for a, b in zip(sorted(sql_rows, key=key), sorted(api_rows, key=key)):
        assert len(a) == len(b), (qname, a, b)
        for x, y in zip(a, b):
            if isinstance(x, float) and isinstance(y, float):
                if math.isnan(x) or math.isnan(y):
                    assert math.isnan(x) and math.isnan(y), (qname, a, b)
                else:
                    assert abs(x - y) <= tol * max(1.0, abs(x), abs(y)), \
                        (qname, a, b)
            else:
                assert x == y, (qname, a, b)


@pytest.mark.parametrize("qname", sorted(TPCH_SQL, key=lambda q: int(q[1:])))
def test_sql_tpch_text(qname):
    from spark_rapids_tpu.api.session import TpuSession

    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    tables = datagen.register_tables(s, _SF)
    sql_rows = s.sql(TPCH_SQL[qname]).collect()
    api_rows = Q.QUERIES[qname](tables).collect()
    _cmp_rows(sql_rows, api_rows, qname)
