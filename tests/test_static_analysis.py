"""Static-analysis gates (ISSUE 3): the linter runs clean over the repo,
every lint rule trips on a deliberately-broken fixture, api_validation's
registry diff is enforced, and the generated docs can never go stale.

These tests are pure host-side (AST + text + subprocess); no jax device
work, so they are cheap enough for tier-1.
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "spark_rapids_tpu")

from spark_rapids_tpu.analysis import lint  # noqa: E402


# ---------------------------------------------------------------------------
# The repo itself is clean (the tier-1 enforcement of `python -m tools.lint`)
# ---------------------------------------------------------------------------

def test_lint_clean_over_repo():
    violations = lint.run(PKG)
    assert not violations, "\n".join(str(v) for v in violations)


def test_lint_cli_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint"], cwd=ROOT,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# Every rule trips on a broken fixture (and the pragma silences it)
# ---------------------------------------------------------------------------

def _rules(violations):
    return {v.rule for v in violations}


def test_rule_host_sync_np_asarray():
    src = "import numpy as np\n\ndef f(x):\n    return np.asarray(x)\n"
    v = lint.lint_source(src, "ops/fixture.py")
    assert _rules(v) == {"host-sync"} and len(v) == 1


def test_rule_host_sync_device_get_and_block_until_ready():
    src = ("import jax\n\ndef f(x):\n"
           "    jax.device_get(x)\n"
           "    return x.block_until_ready()\n")
    v = lint.lint_source(src, "exec/fixture.py")
    assert len(v) == 2 and _rules(v) == {"host-sync"}


def test_rule_host_sync_scalar_readbacks():
    src = ("import jax.numpy as jnp\n\ndef f(x):\n"
           "    a = int(jnp.sum(x))\n"
           "    b = float(jnp.max(x))\n"
           "    c = x.item()\n"
           "    return a, b, c\n")
    v = lint.lint_source(src, "plan/physical.py")
    assert len(v) == 3 and _rules(v) == {"host-sync"}


def test_rule_host_sync_only_in_hot_modules():
    src = "import numpy as np\n\ndef f(x):\n    return np.asarray(x)\n"
    assert lint.lint_source(src, "columnar/fixture.py") == []
    assert lint.lint_source(src, "api/fixture.py") == []


def test_pragma_silences_and_requires_reason():
    ok = ("import numpy as np\n\ndef f(x):\n"
          "    return np.asarray(x)  "
          "# lint: host-sync-ok the one documented sizing sync\n")
    assert lint.lint_source(ok, "ops/fixture.py") == []
    bare = ("import numpy as np\n\ndef f(x):\n"
            "    return np.asarray(x)  # lint: host-sync-ok\n")
    v = lint.lint_source(bare, "ops/fixture.py")
    # a reason-less pragma does NOT silence the sync and is itself flagged
    assert _rules(v) == {"host-sync", "pragma-reason"}


def test_rule_allowlist_helpers_exempt():
    src = ("import jax\n\nclass PipelineWindow:\n"
           "    def _resolve(self, flat):\n"
           "        return jax.device_get(flat)\n")
    assert lint.lint_source(src, "exec/pipeline.py") == []


def test_rule_exec_contract_missing():
    src = ("class TpuFooExec(TpuExec):\n    pass\n\n"
           "class TpuBarExec(TpuExec):\n    CONTRACT = object()\n")
    v = lint.lint_source(src, "plan/physical.py")
    assert len(v) == 1 and v[0].rule == "exec-contract" \
        and "TpuFooExec" in v[0].message


def test_rule_conf_docs_drift_both_directions():
    config_src = (
        'X = _conf("spark.rapids.tpu.sql.foo").doc("d")'
        '.boolean_conf.create_with_default(True)\n'
        'Y = _conf("spark.rapids.tpu.sql.hidden").doc("d").internal()'
        '.boolean_conf.create_with_default(False)\n')
    docs = ("| Name | Description | Default |\n|---|---|---|\n"
            "| spark.rapids.tpu.sql.stale | gone | 1 |\n")
    v = lint.check_conf_docs(config_src, docs)
    msgs = "\n".join(x.message for x in v)
    assert len(v) == 2
    assert "spark.rapids.tpu.sql.foo" in msgs          # registered, undocumented
    assert "spark.rapids.tpu.sql.stale" in msgs        # documented, unregistered
    assert "hidden" not in msgs                        # internal keys exempt


def test_conf_docs_in_sync_now():
    with open(os.path.join(PKG, "config.py")) as f:
        cfg_src = f.read()
    with open(os.path.join(ROOT, "docs", "configs.md")) as f:
        docs = f.read()
    assert lint.check_conf_docs(cfg_src, docs) == []


# ---------------------------------------------------------------------------
# api_validation enforced in tier-1 (registry drift must fail loudly)
# ---------------------------------------------------------------------------

def test_api_validation_reports_no_problems():
    from tools.api_validation import validate
    report = validate()
    assert report["ok"], report["problems"]
    assert report["n_expressions"] > 50
    assert report["n_execs"] > 10


# ---------------------------------------------------------------------------
# Doc-drift gate: generated docs byte-identical to a fresh regeneration.
# Fresh subprocess: per-operator conf keys registered dynamically by earlier
# tests in THIS process must not leak into the regenerated docs.
# ---------------------------------------------------------------------------

def test_generated_docs_not_stale():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "gen_docs.py"),
         "--check"],
        cwd=ROOT, capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
