"""Static-analysis gates (ISSUE 3): the linter runs clean over the repo,
every lint rule trips on a deliberately-broken fixture, api_validation's
registry diff is enforced, and the generated docs can never go stale.

These tests are pure host-side (AST + text + subprocess); no jax device
work, so they are cheap enough for tier-1.
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "spark_rapids_tpu")

from spark_rapids_tpu.analysis import lint  # noqa: E402


# ---------------------------------------------------------------------------
# The repo itself is clean (the tier-1 enforcement of `python -m tools.lint`)
# ---------------------------------------------------------------------------

def test_lint_clean_over_repo():
    violations = lint.run(PKG)
    assert not violations, "\n".join(str(v) for v in violations)


def test_lint_cli_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint"], cwd=ROOT,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# Every rule trips on a broken fixture (and the pragma silences it)
# ---------------------------------------------------------------------------

def _rules(violations):
    return {v.rule for v in violations}


def test_rule_host_sync_np_asarray():
    src = "import numpy as np\n\ndef f(x):\n    return np.asarray(x)\n"
    v = lint.lint_source(src, "ops/fixture.py")
    assert _rules(v) == {"host-sync"} and len(v) == 1


def test_rule_host_sync_device_get_and_block_until_ready():
    src = ("import jax\n\ndef f(x):\n"
           "    jax.device_get(x)\n"
           "    return x.block_until_ready()\n")
    v = lint.lint_source(src, "exec/fixture.py")
    assert len(v) == 2 and _rules(v) == {"host-sync"}


def test_rule_host_sync_scalar_readbacks():
    src = ("import jax.numpy as jnp\n\ndef f(x):\n"
           "    a = int(jnp.sum(x))\n"
           "    b = float(jnp.max(x))\n"
           "    c = x.item()\n"
           "    return a, b, c\n")
    v = lint.lint_source(src, "plan/physical.py")
    assert len(v) == 3 and _rules(v) == {"host-sync"}


def test_rule_host_sync_only_in_hot_modules():
    src = "import numpy as np\n\ndef f(x):\n    return np.asarray(x)\n"
    assert lint.lint_source(src, "columnar/fixture.py") == []
    assert lint.lint_source(src, "api/fixture.py") == []


def test_pragma_silences_and_requires_reason():
    ok = ("import numpy as np\n\ndef f(x):\n"
          "    return np.asarray(x)  "
          "# lint: host-sync-ok the one documented sizing sync\n")
    assert lint.lint_source(ok, "ops/fixture.py") == []
    bare = ("import numpy as np\n\ndef f(x):\n"
            "    return np.asarray(x)  # lint: host-sync-ok\n")
    v = lint.lint_source(bare, "ops/fixture.py")
    # a reason-less pragma does NOT silence the sync and is itself flagged
    assert _rules(v) == {"host-sync", "pragma-reason"}


def test_rule_allowlist_helpers_exempt():
    src = ("import jax\n\nclass PipelineWindow:\n"
           "    def _resolve(self, flat):\n"
           "        return jax.device_get(flat)\n")
    assert lint.lint_source(src, "exec/pipeline.py") == []


def test_rule_exec_contract_missing():
    src = ("class TpuFooExec(TpuExec):\n    pass\n\n"
           "class TpuBarExec(TpuExec):\n"
           "    CONTRACT = object()\n"
           "    METRICS = exec_metrics()\n")
    v = lint.lint_source(src, "plan/physical.py")
    assert len(v) == 1 and v[0].rule == "exec-contract" \
        and "TpuFooExec" in v[0].message


def test_rule_exec_metrics_missing():
    """A CONTRACT-declaring exec without METRICS trips exec-metrics."""
    src = ("class TpuFooExec(TpuExec):\n"
           "    CONTRACT = object()\n")
    v = lint.lint_source(src, "plan/physical.py")
    assert len(v) == 1 and v[0].rule == "exec-metrics" \
        and "TpuFooExec" in v[0].message


def test_base_metric_keys_mirror_in_sync():
    """lint.BASE_METRIC_KEYS is a hand-maintained mirror of
    exec/metrics.BASE_METRICS (the linter cannot import the engine); a
    drift would lint-fail every exec emitting the new key — or exempt a
    dropped one forever."""
    from spark_rapids_tpu.exec import metrics as em
    assert lint.BASE_METRIC_KEYS == set(em.BASE_METRICS)


def test_rule_metric_key_undeclared():
    """A literal metric key not in the class's METRICS trips metric-key —
    both the trace_span metric_key argument and metrics.inc calls; base
    keys (numOutputRows, opTime, hostSyncs, ...) are exempt."""
    src = (
        "class TpuFooExec(TpuExec):\n"
        "    CONTRACT = object()\n"
        '    METRICS = exec_metrics("fooTime")\n'
        "    def _map(self):\n"
        '        with trace_span("foo", self.metrics, "fooTime"):\n'
        "            pass\n"
        '        with trace_span("bar", self.metrics, "barTime"):\n'
        "            pass\n"
        '        self.metrics.inc("numOutputRows", 1)\n'
        '        self.metrics.inc("rogueCounter")\n'
        '        with trace_span("kw", self.metrics,\n'
        '                        metric_key="kwTime"):\n'
        "            pass\n")
    v = lint.lint_source(src, "plan/physical.py")
    rules = [x.rule for x in v]
    msgs = "\n".join(x.message for x in v)
    assert rules == ["metric-key"] * 3, v
    assert "barTime" in msgs and "rogueCounter" in msgs and "kwTime" in msgs
    assert "fooTime" not in msgs and "numOutputRows" not in msgs


def test_rule_telemetry_key_undeclared():
    """A registry.counter/gauge/histogram literal name not declared in
    service/telemetry.TELEMETRY_KEYS trips telemetry-key; declared names
    pass, and a missing TELEMETRY_KEYS surface is itself a violation."""
    decl = ('TELEMETRY_KEYS = (\n    "tpu_good_total",\n'
            '    "tpu_fine_bytes",\n)\n')
    user = ('def publish(reg):\n'
            '    reg.counter("tpu_good_total").inc()\n'
            '    reg.gauge("tpu_fine_bytes", "help", store="x").set(1)\n'
            '    reg.histogram("tpu_rogue_seconds").observe(0.1)\n'
            '    reg.gauge("tpu_unheard_of").set(2)\n')
    v = lint.check_telemetry_keys({
        "service/telemetry.py": ("service/telemetry.py", decl),
        "exec/foo.py": ("exec/foo.py", user)})
    assert [x.rule for x in v] == ["telemetry-key"] * 2, v
    msgs = "\n".join(x.message for x in v)
    assert "tpu_rogue_seconds" in msgs and "tpu_unheard_of" in msgs
    assert "tpu_good_total" not in msgs
    # no TELEMETRY_KEYS tuple at all: the surface itself is flagged
    v2 = lint.check_telemetry_keys({
        "service/telemetry.py": ("service/telemetry.py", "X = 1\n")})
    assert len(v2) == 1 and "TELEMETRY_KEYS" in v2[0].message


def test_rule_querylog_key_undeclared():
    """A top-level record field build_record emits (rec dict literal or
    rec["..."] assign) not declared in QUERY_LOG_FIELDS trips
    querylog-key; declared fields pass; nested dict literals NOT
    assigned to rec are out of scope; a missing QUERY_LOG_FIELDS tuple
    is itself a violation."""
    src = (
        'QUERY_LOG_FIELDS = ("queryId", "wallS")\n'
        'def build_record(session):\n'
        '    inner = {"notAField": 1}\n'
        '    rec = {"queryId": "q1", "wallS": 0.5, "rogueField": inner}\n'
        '    rec["alsoRogue"] = 2\n'
        '    return rec\n')
    v = lint.check_querylog_keys(src, "service/query_log.py")
    assert [x.rule for x in v] == ["querylog-key"] * 2, v
    msgs = "\n".join(x.message for x in v)
    assert "rogueField" in msgs and "alsoRogue" in msgs
    assert "queryId" not in msgs and "notAField" not in msgs
    v2 = lint.check_querylog_keys("X = 1\n", "service/query_log.py")
    assert len(v2) == 1 and "QUERY_LOG_FIELDS" in v2[0].message


def test_querylog_fields_surface_in_sync_now():
    """The live query-log writer emits only declared fields, and the
    declared tuple parses to the engine's exported surface."""
    path = os.path.join(PKG, "service", "query_log.py")
    with open(path) as f:
        src = f.read()
    assert lint.check_querylog_keys(src, path) == []
    from spark_rapids_tpu.service.query_log import QUERY_LOG_FIELDS
    assert lint.querylog_declared_keys(src) == set(QUERY_LOG_FIELDS)


def test_telemetry_keys_surface_in_sync_now():
    """Every registry metric name the package emits is declared (the
    live telemetry-key gate over the real tree), and the declared tuple
    parses to the same set the engine exports."""
    srcs = {}
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, PKG).replace(os.sep, "/")
                with open(full) as f:
                    srcs[rel] = (full, f.read())
    assert lint.check_telemetry_keys(srcs) == []
    from spark_rapids_tpu.service import telemetry as tel
    declared = lint.telemetry_declared_keys(
        srcs["service/telemetry.py"][1])
    assert declared == set(tel.TELEMETRY_KEYS)


def test_rule_conf_docs_drift_both_directions():
    config_src = (
        'X = _conf("spark.rapids.tpu.sql.foo").doc("d")'
        '.boolean_conf.create_with_default(True)\n'
        'Y = _conf("spark.rapids.tpu.sql.hidden").doc("d").internal()'
        '.boolean_conf.create_with_default(False)\n')
    docs = ("| Name | Description | Default |\n|---|---|---|\n"
            "| spark.rapids.tpu.sql.stale | gone | 1 |\n")
    v = lint.check_conf_docs(config_src, docs)
    msgs = "\n".join(x.message for x in v)
    assert len(v) == 2
    assert "spark.rapids.tpu.sql.foo" in msgs          # registered, undocumented
    assert "spark.rapids.tpu.sql.stale" in msgs        # documented, unregistered
    assert "hidden" not in msgs                        # internal keys exempt


def test_conf_docs_in_sync_now():
    with open(os.path.join(PKG, "config.py")) as f:
        cfg_src = f.read()
    with open(os.path.join(ROOT, "docs", "configs.md")) as f:
        docs = f.read()
    assert lint.check_conf_docs(cfg_src, docs) == []


# ---------------------------------------------------------------------------
# Concurrency rules (analysis/concurrency.py, wired into lint.lint_source):
# every rule trips on a broken fixture, pragmas (with reason) silence,
# out-of-scope modules are exempt
# ---------------------------------------------------------------------------

from spark_rapids_tpu.analysis import concurrency  # noqa: E402


def test_rule_raw_lock():
    src = "import threading\n\nl = threading.Lock()\n"
    v = lint.lint_source(src, "exec/fixture.py")
    assert _rules(v) == {"raw-lock"} and len(v) == 1
    ok = ("import threading\n\nl = threading.Lock()  "
          "# lint: raw-lock-ok leaf lock of the instrumentation itself\n")
    assert lint.lint_source(ok, "exec/fixture.py") == []
    # threading.local / Event are confinement + signalling, not flagged
    benign = ("import threading\n\nt = threading.local()\n"
              "e = threading.Event()\n")
    assert lint.lint_source(benign, "exec/fixture.py") == []


def test_rule_raw_lock_lockdep_itself_exempt():
    src = "import threading\n\nl = threading.Lock()\n"
    assert lint.lint_source(src, "analysis/lockdep.py") == []


def test_rule_unguarded_state_lock_owning_class():
    src = ("from spark_rapids_tpu.analysis.lockdep import named_lock\n\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = named_lock('x.C._mu')\n"
           "        self.n = 0\n"                       # ctor exempt
           "    def bump(self):\n"
           "        self.n += 1\n"                      # UNGUARDED
           "    def bump_guarded(self):\n"
           "        with self._mu:\n"
           "            self.n += 1\n"                  # guarded: ok
           "    def _bump_locked(self):\n"
           "        self.n += 1\n")                     # convention: ok
    v = lint.lint_source(src, "exec/fixture.py")
    assert len(v) == 1 and v[0].rule == "unguarded-state"
    assert "C.n" in v[0].message


def test_rule_unguarded_state_lock_free_class_exempt():
    src = ("class C:\n"
           "    def bump(self):\n"
           "        self.n = 1\n")       # no lock owned: thread-confined
    assert lint.lint_source(src, "exec/fixture.py") == []


def test_rule_unguarded_state_module_global():
    src = ("from spark_rapids_tpu.analysis.lockdep import named_lock\n"
           "_mu = named_lock('x._mu')\n"
           "_cache = None\n\n"
           "def prime(v):\n"
           "    global _cache\n"
           "    _cache = v\n")                          # UNGUARDED
    v = lint.lint_source(src, "analysis/fixture.py")
    assert len(v) == 1 and v[0].rule == "unguarded-state"
    guarded = ("from spark_rapids_tpu.analysis.lockdep import named_lock\n"
               "_mu = named_lock('x._mu')\n"
               "_cache = None\n\n"
               "def prime(v):\n"
               "    global _cache\n"
               "    with _mu:\n"
               "        _cache = v\n")
    assert lint.lint_source(guarded, "analysis/fixture.py") == []


def test_rule_unguarded_state_threading_local_exempt():
    src = ("import threading\n"
           "from spark_rapids_tpu.analysis.lockdep import named_lock\n\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._mu = named_lock('x2.C._mu')\n"
           "        self._tls = threading.local()\n"
           "    def mark(self):\n"
           "        self._tls.value = 1\n")   # through thread-local: ok
    assert lint.lint_source(src, "exec/fixture.py") == []


def test_rule_lock_blocking_io_and_readback():
    src = ("import numpy as np\n"
           "from spark_rapids_tpu.analysis.lockdep import named_rlock\n\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = named_rlock('x3.C._lock')\n"
           "    def bad(self, path, arrs):\n"
           "        with self._lock:\n"
           "            np.savez(path, *arrs)\n"        # disk IO under lock
           "            h = [np.asarray(a) for a in arrs]\n"  # readback
           "            return h\n")
    v = lint.lint_source(src, "exec/fixture.py")
    rules = [x.rule for x in v]
    assert rules.count("lock-blocking") == 2, v
    assert any("np.savez" in x.message for x in v)
    assert any("np.asarray" in x.message for x in v)


def test_rule_lock_blocking_nested_lock_and_pragma():
    src = ("from spark_rapids_tpu.analysis.lockdep import named_lock\n\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._a_lock = named_lock('x4.C.a')\n"
           "        self._b_lock = named_lock('x4.C.b')\n"
           "    def nested(self):\n"
           "        with self._a_lock:\n"
           "            with self._b_lock:\n"
           "                pass\n")
    v = lint.lint_source(src, "exec/fixture.py")
    assert _rules(v) == {"lock-blocking"}
    assert "nested acquisition" in v[0].message
    ok = src.replace(
        "        with self._a_lock:\n",
        "        with self._a_lock:\n"
        "            # lint: lock-blocking-ok documented order a < b\n")
    assert lint.lint_source(ok, "exec/fixture.py") == []


def test_rule_lock_blocking_not_flagged_outside_lock():
    src = ("import numpy as np\n\n"
           "def f(path, arrs):\n"
           "    np.savez(path, *arrs)\n")
    v = lint.lint_source(src, "shuffle/fixture.py")
    assert "lock-blocking" not in _rules(v)


def test_rule_singleton_guard():
    src = ("import threading\n\n"
           "class S:\n"
           "    _instance = None\n"
           "    _lock = threading.Lock()  # lint: raw-lock-ok fixture\n"
           "    @classmethod\n"
           "    def get(cls):\n"
           "        if cls._instance is None:\n"        # UNGUARDED read
           "            with cls._lock:\n"
           "                cls._instance = S()\n"      # guarded write: ok
           "        return cls._instance\n")            # UNGUARDED read
    v = lint.lint_source(src, "exec/fixture.py")
    assert [x.rule for x in v] == ["singleton-guard", "singleton-guard"]
    ok = ("import threading\n\n"
          "class S:\n"
          "    _instance = None\n"
          "    _lock = threading.Lock()  # lint: raw-lock-ok fixture\n"
          "    @classmethod\n"
          "    def get(cls):\n"
          "        with cls._lock:\n"
          "            if cls._instance is None:\n"
          "                cls._instance = S()\n"
          "            return cls._instance\n")
    assert lint.lint_source(ok, "exec/fixture.py") == []


def test_rule_concurrency_pragma_requires_reason():
    src = ("import threading\n\nl = threading.Lock()  # lint: raw-lock-ok\n")
    v = lint.lint_source(src, "exec/fixture.py")
    # a reason-less pragma does NOT silence and is itself flagged
    assert _rules(v) == {"raw-lock", "pragma-reason"}


def test_concurrency_rules_scoped_to_thread_reachable_modules():
    src = ("import threading\n\nl = threading.Lock()\n")
    assert lint.lint_source(src, "columnar/fixture.py") == []
    assert lint.lint_source(src, "plan/fixture.py") == []


def test_rule_lock_name_dup():
    mk = lambda rel, line: concurrency.LockSite(
        path=rel, rel=rel, line=line, kind="named_lock",
        attr="_mu", canonical="dup.name")
    v = concurrency.check_registry([mk("exec/a.py", 3), mk("exec/b.py", 9)])
    assert len(v) == 1 and v[0].rule == "lock-name-dup"
    # same site re-parsed twice is NOT a dup
    assert concurrency.check_registry(
        [mk("exec/a.py", 3), mk("exec/a.py", 3)]) == []


def test_lock_registry_covers_engine_locks():
    sites = concurrency.lock_registry(PKG)
    names = {s.canonical for s in sites}
    for expected in ("exec.spill.BufferCatalog._mu",
                     "exec.spill.SpillableBuffer._lock",
                     "exec.device.TpuSemaphore._stats_mu",
                     "shuffle.transport.ShuffleStore._mu",
                     "api.session.TpuSession._lock",
                     "config.ConfRegistry._lock"):
        assert expected in names, f"{expected} missing from registry"


# ---------------------------------------------------------------------------
# api_validation enforced in tier-1 (registry drift must fail loudly)
# ---------------------------------------------------------------------------

def test_api_validation_reports_no_problems():
    from tools.api_validation import validate
    report = validate()
    assert report["ok"], report["problems"]
    assert report["n_expressions"] > 50
    assert report["n_execs"] > 10


# ---------------------------------------------------------------------------
# Doc-drift gate: generated docs byte-identical to a fresh regeneration.
# Fresh subprocess: per-operator conf keys registered dynamically by earlier
# tests in THIS process must not leak into the regenerated docs.
# ---------------------------------------------------------------------------

def test_generated_docs_not_stale():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "gen_docs.py"),
         "--check"],
        cwd=ROOT, capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# naked-jit: every jax.jit( call inside a _fused_fn builder or pragma'd
# ---------------------------------------------------------------------------

def test_rule_naked_jit_flags_escaped_compile():
    src = ("import jax\n\ndef f(x):\n"
           "    return jax.jit(lambda y: y + 1)(x)\n")
    v = lint.lint_source(src, "ops/fixture.py")
    assert "naked-jit" in _rules(v)
    assert any("recompile audit" in x.message for x in v)


def test_rule_naked_jit_sanctions_fused_fn_builders():
    """A jit inside a function passed as _fused_fn's builder argument —
    directly, as a bound method, or wrapped in a lambda — is inside the
    audit funnel and clean."""
    direct = ("import jax\n\ndef go(sig):\n"
              "    def build():\n"
              "        def fn(x):\n"
              "            return x\n"
              "        return jax.jit(fn)\n"
              "    return _fused_fn(sig, build)\n")
    assert lint.lint_source(direct, "plan/fixture.py") == []
    wrapped = ("import jax\n\nclass Stage:\n"
               "    def _build(self, donate):\n"
               "        return jax.jit(lambda x: x, donate_argnums=donate)\n"
               "    def run(self, key, donate):\n"
               "        return _fused_fn(key, lambda: self._build(donate))\n")
    assert lint.lint_source(wrapped, "plan/fixture.py") == []


def test_rule_naked_jit_pragma_requires_reason():
    ok = ("import jax\n\ndef f(x):\n"
          "    return jax.jit(lambda y: y)(x)  "
          "# lint: naked-jit-ok own cache audited via note_build\n")
    assert lint.lint_source(ok, "ops/fixture.py") == []
    bare = ("import jax\n\ndef f(x):\n"
            "    return jax.jit(lambda y: y)(x)  # lint: naked-jit-ok\n")
    v = lint.lint_source(bare, "ops/fixture.py")
    assert _rules(v) == {"naked-jit", "pragma-reason"}


def test_rule_bare_recover_flags_taxonomy_catch():
    src = ("def f():\n"
           "    try:\n"
           "        pass\n"
           "    except ShuffleFetchError:\n"
           "        pass\n")
    v = lint.lint_source(src, "shuffle/fixture.py")
    assert "bare-recover" in _rules(v)
    assert any("stage-retry driver" in x.message for x in v)
    # tuple and dotted forms are caught too
    tup = ("def f():\n"
           "    try:\n"
           "        pass\n"
           "    except (transport.ShuffleWorkerLostError, ValueError):\n"
           "        pass\n")
    assert "bare-recover" in _rules(lint.lint_source(tup, "exec/fix.py"))
    # the recovery.recoverable_types() call form — the WHOLE taxonomy at
    # once — cannot bypass the rule either
    call = ("def f():\n"
            "    try:\n"
            "        pass\n"
            "    except recovery.recoverable_types():\n"
            "        pass\n")
    assert "bare-recover" in _rules(lint.lint_source(call, "plan/fix.py"))


def test_rule_bare_recover_pragma_and_recovery_module_exempt():
    pragma = ("def f():\n"
              "    try:\n"
              "        pass\n"
              "    except BufferLostError:  "
              "# lint: recover-ok relabeling boundary, never retries\n"
              "        pass\n")
    assert lint.lint_source(pragma, "shuffle/fixture.py") == []
    bare_pragma = ("def f():\n"
                   "    try:\n"
                   "        pass\n"
                   "    except BufferLostError:  # lint: recover-ok\n"
                   "        pass\n")
    v = lint.lint_source(bare_pragma, "shuffle/fixture.py")
    assert _rules(v) == {"bare-recover", "pragma-reason"}
    # exec/recovery.py is the driver's own domain: bare catches legal
    driver = ("def retry():\n"
              "    try:\n"
              "        pass\n"
              "    except (ShuffleFetchError, InjectedTaskFault):\n"
              "        pass\n")
    assert lint.lint_source(driver, "exec/recovery.py") == []
    # non-taxonomy exceptions never trip the rule
    other = ("def f():\n"
             "    try:\n"
             "        pass\n"
             "    except ValueError:\n"
             "        pass\n")
    assert lint.lint_source(other, "shuffle/fixture.py") == []


# ---------------------------------------------------------------------------
# Determinism rules (ISSUE 18): every rule trips on a fixture, the
# nondeterminism-ok pragma (with reason) silences, scope is enforced,
# and the LOCKSTEP_IDS registry round-trips against the live tree
# ---------------------------------------------------------------------------

from spark_rapids_tpu.analysis import determinism  # noqa: E402


def test_rule_nondet_clock_assign_to_id_sink():
    src = ("import time\n\ndef f():\n"
           "    shuffle_id = time.time_ns()\n"
           "    return shuffle_id\n")
    v = lint.lint_source(src, "shuffle/fixture.py")
    assert _rules(v) == {"nondet-clock"} and len(v) == 1
    # clocks feeding NON-id sinks (deadlines, timings) are fine
    ok = ("import time\n\ndef f():\n"
          "    started = time.perf_counter()\n    return started\n")
    assert lint.lint_source(ok, "shuffle/fixture.py") == []


def test_rule_nondet_clock_feeds_id_callee():
    src = ("import time\n\ndef mint_id(v):\n    return v\n\n"
           "def f():\n    return mint_id(time.time())\n")
    v = lint.lint_source(src, "plan/fixture.py")
    assert "nondet-clock" in _rules(v)


def test_rule_nondet_random():
    src = ("import random\n\ndef pick(parts):\n"
           "    return parts[random.randint(0, len(parts) - 1)]\n")
    v = lint.lint_source(src, "parallel/fixture.py")
    assert _rules(v) == {"nondet-random"} and len(v) == 1
    # a seeded instance RNG does not trip the rule
    ok = ("import random\n\ndef pick(parts, seed):\n"
          "    rng = random.Random(seed)\n"
          "    return parts[rng.randint(0, len(parts) - 1)]\n")
    assert lint.lint_source(ok, "parallel/fixture.py") == []


def test_rule_nondet_set_order():
    src = ("def f(a, b):\n"
           "    out = []\n"
           "    for x in set(a) | set(b):\n"
           "        out.append(x)\n"
           "    return out\n")
    # the for-loop iterates a binop, not a direct set expr — but the
    # canonical direct forms all trip:
    direct = "def f():\n    for x in {1, 2, 3}:\n        pass\n"
    v = lint.lint_source(direct, "plan/fixture.py")
    assert _rules(v) == {"nondet-set-order"}
    wrapped = ("def f(items):\n"
               "    return list(set(items))\n")
    v = lint.lint_source(wrapped, "plan/fixture.py")
    assert _rules(v) == {"nondet-set-order"}
    ok = ("def f(items):\n"
          "    return sorted(set(items))\n")
    assert lint.lint_source(ok, "plan/fixture.py") == []


def test_rule_nondet_scan():
    src = ("import os\n\ndef f(d):\n"
           "    return [p for p in os.listdir(d)]\n")
    v = lint.lint_source(src, "shuffle/fixture.py")
    assert _rules(v) == {"nondet-scan"} and len(v) == 1
    ok = ("import os\n\ndef f(d):\n"
          "    return [p for p in sorted(os.listdir(d))]\n")
    assert lint.lint_source(ok, "shuffle/fixture.py") == []
    g = ("import glob\n\ndef f(d):\n"
         "    return glob.glob(d + '/*.bin')\n")
    assert _rules(lint.lint_source(g, "shuffle/fixture.py")) == \
        {"nondet-scan"}


def test_rule_lockstep_id_undeclared_mint_sites():
    count_src = ("import itertools\n\n"
                 "_rogue_seq = itertools.count(1)\n")
    v = lint.lint_source(count_src, "shuffle/fixture.py")
    assert _rules(v) == {"lockstep-id"}
    assert "shuffle.fixture._rogue_seq" in v[0].message
    counter_src = ("class W:\n"
                   "    def nxt(self):\n"
                   "        self._next_token += 1\n"
                   "        return self._next_token\n")
    v = lint.lint_source(counter_src, "plan/fixture.py")
    assert _rules(v) == {"lockstep-id"}
    assert "plan.fixture.W._next_token" in v[0].message


def test_determinism_rules_only_in_lockstep_scope():
    src = ("import random\nimport os\n\ndef f(d):\n"
           "    random.random()\n"
           "    return os.listdir(d)\n")
    assert lint.lint_source(src, "api/fixture.py") == []
    assert lint.lint_source(src, "service/fixture.py") == []
    assert _rules(lint.lint_source(src, "shuffle/fixture.py")) == \
        {"nondet-random", "nondet-scan"}


def test_nondeterminism_pragma_silences_and_requires_reason():
    ok = ("import random\n\ndef f():\n"
          "    return random.random()  "
          "# lint: nondeterminism-ok jitter only, never feeds an id\n")
    assert lint.lint_source(ok, "shuffle/fixture.py") == []
    # the line-above placement works too
    above = ("import random\n\ndef f():\n"
             "    # lint: nondeterminism-ok jitter only\n"
             "    return random.random()\n")
    assert lint.lint_source(above, "shuffle/fixture.py") == []
    bare = ("import random\n\ndef f():\n"
            "    return random.random()  # lint: nondeterminism-ok\n")
    v = lint.lint_source(bare, "shuffle/fixture.py")
    assert _rules(v) == {"nondet-random", "pragma-reason"}


def test_lockstep_id_registry_roundtrip():
    sites = determinism.id_registry(PKG)
    found = {s.canonical for s in sites}
    # every declared stream exists in the tree...
    for name in determinism.LOCKSTEP_IDS:
        assert name in found, name
    assert not determinism.check_registry(sites)
    # ...and a stale declared entry is flagged
    stale = determinism.check_registry(
        [], declared=("shuffle.manager.WorkerContext._gone",))
    assert len(stale) == 1 and stale[0].rule == "lockstep-id"
    assert "stale registry" in stale[0].message


def test_lint_json_reports_pragma_inventory():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--json"], cwd=ROOT,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json
    doc = json.loads(proc.stdout)
    assert doc["violations"] == []
    prag = [p for p in doc["pragmas"] if p["rule"] == "nondeterminism"]
    assert prag, "expected nondeterminism-ok pragmas in the tree"
    assert all(p["reason"] and p["suppresses"] for p in prag)


# ---------------------------------------------------------------------------
# Ownership rules (analysis/ownership.py, ISSUE 19): use-after-donate /
# unreleased-acquire / double-free / untracked-residency
# ---------------------------------------------------------------------------

from spark_rapids_tpu.analysis import ownership  # noqa: E402


def test_rule_use_after_donate_array_read():
    src = ("def f(batch):\n"
           "    donate = _donate_argnums(batch, 1)\n"
           "    outs = _fused_fn(sig, build)(n, *batch.flat_arrays())\n"
           "    return process(*batch.flat_arrays()), outs\n")
    v = lint.lint_source(src, "exec/fixture.py")
    assert _rules(v) == {"use-after-donate"}
    assert "flat_arrays" in v[0].message


def test_rule_use_after_donate_bound_fn_and_handoff():
    src = ("def f(batch):\n"
           "    donate = _donate_argnums(batch, 1)\n"
           "    fn = _fused_fn(sig, build)\n"
           "    outs = fn(n, *batch.flat_arrays())\n"
           "    return concat_batches(schema, batch)\n")
    v = lint.lint_source(src, "exec/fixture.py")
    assert _rules(v) == {"use-after-donate"}
    assert "concat_batches" in v[0].message


def test_use_after_donate_metadata_and_probe_exempt():
    # metadata reads survive donation (only the flat arrays die), the
    # _donation_consumed/_note_donated funnels are legal, and an except
    # handler's guarded re-read is the documented failure-path idiom
    src = ("def f(batch):\n"
           "    donate = _donate_argnums(batch, 1)\n"
           "    try:\n"
           "        outs = _fused_fn(sig, build)(n, *batch.flat_arrays())\n"
           "        _note_donated(batch, donate)\n"
           "    except Exception:\n"
           "        if _donation_consumed(batch):\n"
           "            raise\n"
           "        return eager(batch.columns)\n"
           "    return ColumnarBatch.from_flat_arrays(\n"
           "        schema, list(outs), batch.num_rows)\n")
    assert lint.lint_source(src, "exec/fixture.py") == []


def test_use_after_donate_sibling_branch_not_flagged():
    # code past the donated branch's return belongs to a sibling branch
    # the donated invocation never reaches
    src = ("def f(batch, reduce):\n"
           "    donate = _donate_argnums(batch, 1)\n"
           "    if reduce:\n"
           "        outs = _fused_fn(sig, build)(n, *batch.flat_arrays())\n"
           "        return outs\n"
           "    return other_dispatch(batch)\n")
    assert lint.lint_source(src, "exec/fixture.py") == []


def test_rule_unreleased_acquire():
    src = ("def g(b):\n"
           "    handle = SpillableColumnarBatch(b)\n"
           "    return 1\n")
    v = lint.lint_source(src, "exec/fixture.py")
    assert _rules(v) == {"unreleased-acquire"}
    assert "handle" in v[0].message


def test_unreleased_acquire_release_escape_and_with_exempt():
    released = ("def g(b):\n"
                "    handle = SpillableColumnarBatch(b)\n"
                "    try:\n"
                "        return handle.get_batch()\n"
                "    finally:\n"
                "        handle.close()\n")
    assert lint.lint_source(released, "exec/fixture.py") == []
    escaped = ("def g(b):\n"
               "    handle = SpillableColumnarBatch(b)\n"
               "    return handle\n")
    assert lint.lint_source(escaped, "exec/fixture.py") == []
    with_bound = ("def g(b):\n"
                  "    with SpillableColumnarBatch(b) as handle:\n"
                  "        return handle.get_batch()\n")
    assert lint.lint_source(with_bound, "exec/fixture.py") == []
    staged = ("def g(n):\n"
              "    win = _staging_acquire(n)\n"
              "    try:\n"
              "        return fill(win)\n"
              "    finally:\n"
              "        _staging_release(win)\n")
    assert lint.lint_source(staged, "io/fixture.py") == []


def test_rule_double_free():
    src = ("def h(b):\n"
           "    handle = SpillableColumnarBatch(b)\n"
           "    handle.close()\n"
           "    handle.close()\n")
    v = lint.lint_source(src, "exec/fixture.py")
    assert _rules(v) == {"double-free"}
    remove = ("def r(self, bid):\n"
              "    self.catalog.remove(bid)\n"
              "    self.catalog.remove(bid)\n")
    v = lint.lint_source(remove, "exec/fixture.py")
    assert _rules(v) == {"double-free"}


def test_double_free_cleanup_and_rebind_exempt():
    cleanup = ("def h(b):\n"
               "    handle = SpillableColumnarBatch(b)\n"
               "    try:\n"
               "        handle.close()\n"
               "    finally:\n"
               "        handle.close()\n")
    assert lint.lint_source(cleanup, "exec/fixture.py") == []
    rebound = ("def h(b, c):\n"
               "    handle = SpillableColumnarBatch(b)\n"
               "    handle.close()\n"
               "    handle = SpillableColumnarBatch(c)\n"
               "    handle.close()\n")
    assert lint.lint_source(rebound, "exec/fixture.py") == []


def test_rule_untracked_residency():
    src = ("_CACHE = {}\n\n"
           "def c(schema, arrays, n):\n"
           "    _CACHE[n] = ColumnarBatch.from_flat_arrays("
           "schema, arrays, n)\n")
    v = lint.lint_source(src, "exec/fixture.py")
    assert _rules(v) == {"untracked-residency"}
    assert "_CACHE" in v[0].message
    appended = ("_RING = []\n\n"
                "def c(x):\n"
                "    _RING.append(jnp.asarray(x))\n")
    v = lint.lint_source(appended, "columnar/fixture.py")
    assert _rules(v) == {"untracked-residency"}


def test_untracked_residency_host_values_and_locals_exempt():
    host = ("_CACHE = {}\n\n"
            "def c(k, v):\n"
            "    _CACHE[k] = str(v)\n")
    assert lint.lint_source(host, "exec/fixture.py") == []
    local = ("def c(schema, arrays, n):\n"
             "    cache = {}\n"
             "    cache[n] = ColumnarBatch.from_flat_arrays("
             "schema, arrays, n)\n"
             "    return cache\n")
    assert lint.lint_source(local, "exec/fixture.py") == []


def test_ownership_pragma_silences_and_requires_reason():
    ok = ("_CACHE = {}\n\n"
          "def c(k, v):\n"
          "    # lint: ownership-ok bounded per-shape cache by design\n"
          "    _CACHE[k] = jnp.asarray(v)\n")
    assert lint.lint_source(ok, "exec/fixture.py") == []
    bare = ("_CACHE = {}\n\n"
            "def c(k, v):\n"
            "    _CACHE[k] = jnp.asarray(v)  # lint: ownership-ok\n")
    v = lint.lint_source(bare, "exec/fixture.py")
    assert _rules(v) == {"untracked-residency", "pragma-reason"}


def test_ownership_rules_only_in_buffer_scope():
    src = ("def g(b):\n"
           "    handle = SpillableColumnarBatch(b)\n"
           "    return 1\n")
    assert lint.lint_source(src, "api/fixture.py") == []
    assert lint.lint_source(src, "service/fixture.py") == []
    assert _rules(lint.lint_source(src, "shuffle/fixture.py")) == \
        {"unreleased-acquire"}


def test_ownership_sink_registry_roundtrip():
    defined = ownership.sink_registry(PKG)
    # every declared sink resolves to a definition in the tree...
    assert not ownership.check_registry(defined)
    # ...and a stale declared entry is flagged
    stale = ownership.check_registry(defined - {"exec.spill.defer_finalizer"})
    assert len(stale) == 1 and stale[0].rule == "ownership-registry"
    assert "defer_finalizer" in stale[0].message


# ---------------------------------------------------------------------------
# cancel-point: blocking loops in drain/fetch modules must poll the token
# ---------------------------------------------------------------------------

def test_rule_cancel_point_flags_unpolled_while():
    src = ("import time\n\ndef drain(q):\n"
           "    while True:\n"
           "        time.sleep(0.01)\n")
    v = lint.lint_source(src, "exec/tasks.py")
    assert "cancel-point" in _rules(v)
    assert any(v_.rule == "cancel-point" and v_.line == 4 for v_ in v)


def test_rule_cancel_point_poll_satisfies():
    src = ("import time\nfrom .lifecycle import check_cancel\n\n"
           "def drain(q):\n"
           "    while True:\n"
           "        check_cancel()\n"
           "        time.sleep(0.01)\n")
    assert "cancel-point" not in _rules(
        lint.lint_source(src, "exec/tasks.py"))
    dotted = ("import time\n\ndef drain(q):\n"
              "    while True:\n"
              "        lifecycle.interruptible_sleep(0.5)\n")
    assert "cancel-point" not in _rules(
        lint.lint_source(dotted, "shuffle/transport.py"))


def test_rule_cancel_point_pragma_and_reason():
    ok = ("def serve(sock):\n"
          "    while True:  # lint: cancel-ok server conn thread, "
          "no ambient query\n"
          "        sock.recv(4)\n")
    assert lint.lint_source(ok, "shuffle/transport.py") == []
    bare = ("def serve(sock):\n"
            "    while True:  # lint: cancel-ok\n"
            "        sock.recv(4)\n")
    v = lint.lint_source(bare, "shuffle/transport.py")
    # a reason-less pragma does not silence the loop and is itself flagged
    assert _rules(v) == {"cancel-point", "pragma-reason"}


def test_rule_cancel_point_scoped_to_drain_modules():
    src = ("import time\n\ndef spin():\n"
           "    while True:\n"
           "        time.sleep(0.01)\n")
    assert "cancel-point" not in _rules(
        lint.lint_source(src, "api/fixture.py"))
    assert "cancel-point" not in _rules(
        lint.lint_source(src, "service/fixture.py"))


def test_rule_cancel_point_for_requires_blocking_call():
    # a plain for loop is bounded work: exempt without a pragma
    plain = ("def f(items):\n"
             "    for it in items:\n"
             "        handle(it)\n")
    assert lint.lint_source(plain, "exec/tasks.py") == []
    # a for loop that parks the thread (ev.wait) is a dwell: flagged
    blocking = ("def f(items, ev):\n"
                "    for it in items:\n"
                "        ev.wait(1.0)\n")
    v = lint.lint_source(blocking, "exec/tasks.py")
    assert any(v_.rule == "cancel-point" and "blocking-for"
               in v_.message for v_ in v)
