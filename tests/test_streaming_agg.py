"""Streaming aggregation + spillable execution state.

Reference analog: the per-batch update/merge hot loop (aggregate.scala:427-485)
with the running aggregate held as a SpillableColumnarBatch, plus the
GpuSemaphore/reserve admission contract (GpuSemaphore.scala:74-78,
DeviceMemoryEventHandler.scala:42-69).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.exec.device import TpuSemaphore
from spark_rapids_tpu.exec.spill import BufferCatalog
from spark_rapids_tpu.plan import logical as lp
from spark_rapids_tpu.plan.physical import (TpuHashAggregateExec,
                                            TpuLocalScanExec,
                                            TpuSortMergeJoinExec)
from spark_rapids_tpu.ops import expressions as ex


def _scan(df: pd.DataFrame, batch_rows: int, num_partitions: int = 1):
    table = pa.Table.from_pandas(df, preserve_index=False)
    schema = dt.Schema([dt.Field(f.name, dt.from_arrow(f.type), f.nullable)
                        for f in table.schema])
    return TpuLocalScanExec(table, schema, batch_rows=batch_rows,
                            num_partitions=num_partitions)


def _resolve_all(exprs, schema):
    for e in exprs:
        for ref in e.collect(lambda x: isinstance(x, ex.ColumnRef)):
            ref.resolve(schema)
    return exprs


def _agg_exprs(schema):
    g = ex.ColumnRef("k")
    leaf_sum = lp.AggregateExpression("sum", ex.ColumnRef("v"))
    leaf_cnt = lp.AggregateExpression("count", ex.ColumnRef("v"))
    leaf_avg = lp.AggregateExpression("avg", ex.ColumnRef("v"))
    return _resolve_all([g, leaf_sum, leaf_cnt, leaf_avg], schema)


def _agg_over(scan, mode="complete"):
    exprs = _agg_exprs(scan.schema)
    return TpuHashAggregateExec(scan, [exprs[0]], exprs, mode=mode)


def _collect_rows(exec_node):
    rows = []
    for part in exec_node.execute():
        for batch in part:
            d = batch.to_pydict()
            names = list(d.keys())
            rows.extend(zip(*[d[n] for n in names]))
    return rows


@pytest.fixture
def small_budget():
    cat = BufferCatalog.get()
    saved = cat.device_budget
    saved_spilled = cat.spilled_device_bytes
    # partial batches are compacted to bucket(n_groups) capacity, so the
    # running state is a few KB: the budget must undercut even that to
    # exercise the spill path
    cat.device_budget = 2 * 1024
    yield cat
    cat.device_budget = saved


def test_streaming_agg_30_batches_under_tiny_budget(small_budget):
    """30 batches whose concat would blow the device budget aggregate
    correctly batch-by-batch, spilling the running partial as needed."""
    rng = np.random.default_rng(3)
    n = 200_000                              # ~49 batches of 4096 rows
    df = pd.DataFrame({"k": rng.integers(0, 100, n),
                       "v": rng.normal(0, 10, n)})
    total_bytes = n * 16
    assert total_bytes > small_budget.device_budget * 10

    agg = _agg_over(_scan(df, batch_rows=4096, num_partitions=3))
    rows = _collect_rows(agg)
    exp = df.groupby("k")["v"].agg(["sum", "count", "mean"])
    assert len(rows) == len(exp)
    got = {int(r[0]): r[1:] for r in rows}
    for k, row in exp.iterrows():
        s, c, a = got[int(k)]
        assert c == row["count"]
        assert s == pytest.approx(row["sum"], rel=1e-6, abs=1e-6)
        assert a == pytest.approx(row["mean"], rel=1e-6, abs=1e-6)
    assert small_budget.spilled_device_bytes > 0, \
        "expected the tiny budget to force device->host spill"


def test_partial_final_compose_across_partitions(small_budget):
    """partial (per partition) -> final (merge) matches a one-shot complete
    aggregation — the two-phase plan the exchange composes."""
    rng = np.random.default_rng(9)
    n = 20_000
    df = pd.DataFrame({"k": rng.integers(0, 40, n),
                       "v": rng.normal(0, 5, n)})
    scan = _scan(df, batch_rows=1024, num_partitions=5)
    partial = _agg_over(scan, mode="partial")
    exprs = _agg_exprs(scan.schema)
    final = TpuHashAggregateExec(partial, [exprs[0]], exprs, mode="final")
    rows = _collect_rows(final)
    exp = df.groupby("k")["v"].agg(["sum", "count", "mean"])
    assert len(rows) == len(exp)
    got = {int(r[0]): r[1:] for r in rows}
    for k, row in exp.iterrows():
        s, c, a = got[int(k)]
        assert c == row["count"]
        assert s == pytest.approx(row["sum"], rel=1e-6, abs=1e-6)
        assert a == pytest.approx(row["mean"], rel=1e-6, abs=1e-6)


def test_join_build_side_spillable(small_budget):
    """Join whose build side arrives as many batches under a tiny budget."""
    rng = np.random.default_rng(5)
    n_b, n_s = 30_000, 2_000
    right = pd.DataFrame({"k": np.arange(n_b) % 500,
                          "w": rng.integers(0, 1000, n_b)})
    left = pd.DataFrame({"k": rng.integers(0, 500, n_s),
                         "v": rng.integers(0, 1000, n_s)})
    jk = ex.ColumnRef("k")
    join = TpuSortMergeJoinExec(_scan(left, batch_rows=1024),
                                _scan(right, batch_rows=1024,
                                      num_partitions=4),
                                "inner", [jk], [jk])
    rows = _collect_rows(join)
    exp = left.merge(right, on="k", how="inner")
    assert len(rows) == len(exp)


def test_semaphore_and_reserve_invoked_by_execution():
    """The memory runtime is wired into the execution path: a simple query
    acquires the task semaphore and admission-checks device materializations
    (round-1 VERDICT weak#4: these must not be dead code)."""
    acquires = []
    reserves = []
    orig_acq = TpuSemaphore.acquire_if_necessary
    orig_res = BufferCatalog.reserve
    TpuSemaphore.acquire_if_necessary = \
        lambda self: (acquires.append(1), orig_acq(self))[1]
    BufferCatalog.reserve = \
        lambda self, n: (reserves.append(n), orig_res(self, n))[1]
    try:
        df = pd.DataFrame({"k": [1, 2, 1, 2], "v": [1.0, 2.0, 3.0, 4.0]})
        agg = _agg_over(_scan(df, batch_rows=2))
        rows = _collect_rows(agg)
        assert len(rows) == 2
    finally:
        TpuSemaphore.acquire_if_necessary = orig_acq
        BufferCatalog.reserve = orig_res
    assert len(acquires) >= 1, "semaphore never acquired"
    assert len(reserves) >= 2, "reserve never called for materializations"


def test_planner_inserts_coalesce_batches():
    """The transition pass plans TpuCoalesceBatchesExec per coalesce goals
    (round-1 VERDICT: coalesce was planner-dead code)."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    s = TpuSession.builder.getOrCreate()
    df = (s.createDataFrame({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
          .groupby("k").agg(F.sum("v").alias("s"))
          .sort("k"))
    df.collect()
    tree = s._last_exec_plan._tree_string()
    assert "TpuCoalesceBatchesExec" in tree, tree
