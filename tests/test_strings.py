"""String expression tests. Reference analog: string suites + stringFunctions
semantics (SURVEY.md §2.3)."""

import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Scalar
from spark_rapids_tpu.ops import strings as S
from spark_rapids_tpu.ops.expressions import col, lit


def _batch(**cols):
    return ColumnarBatch.from_pydict(cols)


def _eval(expr, batch):
    expr = expr.transform(
        lambda e: e.resolve(batch.schema) if hasattr(e, "resolve") else None)
    out = expr.eval(batch)
    if isinstance(out, Scalar):
        return out.value
    return out.to_pylist(batch.num_rows)


def test_length_chars_not_bytes():
    b = _batch(s=["hello", "", None, "héllo", "日本語"])
    assert _eval(S.Length(col("s")), b) == [5, 0, None, 5, 3]


def test_upper_lower():
    b = _batch(s=["MiXeD", "abc", None])
    assert _eval(S.Upper(col("s")), b) == ["MIXED", "ABC", None]
    assert _eval(S.Lower(col("s")), b) == ["mixed", "abc", None]


def test_initcap():
    b = _batch(s=["hello world", "ABC def", None])
    assert _eval(S.InitCap(col("s")), b) == ["Hello World", "Abc Def", None]


def test_substring():
    b = _batch(s=["hello", "hi", None])
    assert _eval(S.Substring(col("s"), lit(2), lit(3)), b) == ["ell", "i", None]
    assert _eval(S.Substring(col("s"), lit(0), lit(2)), b) == ["he", "hi", None]
    assert _eval(S.Substring(col("s"), lit(-3), lit(2)), b) == ["ll", "hi", None]


def test_substring_multibyte():
    b = _batch(s=["héllo"])
    assert _eval(S.Substring(col("s"), lit(2), lit(2)), b) == ["él"]


def test_concat():
    b = _batch(a=["x", "y", None], c=["1", "2", "3"])
    assert _eval(S.ConcatStr(col("a"), lit("-"), col("c")), b) == \
        ["x-1", "y-2", None]


def test_contains_starts_ends():
    b = _batch(s=["foobar", "barfoo", "baz", None])
    assert _eval(S.Contains(col("s"), lit("foo")), b) == [True, True, False, None]
    assert _eval(S.StartsWith(col("s"), lit("foo")), b) == [True, False, False, None]
    assert _eval(S.EndsWith(col("s"), lit("foo")), b) == [False, True, False, None]


def test_like():
    b = _batch(s=["apple", "application", "grape", None])
    assert _eval(S.Like(col("s"), "app%"), b) == [True, True, False, None]
    assert _eval(S.Like(col("s"), "%ple"), b) == [True, False, False, None]
    assert _eval(S.Like(col("s"), "%pl%"), b) == [True, True, False, None]
    assert _eval(S.Like(col("s"), "apple"), b) == [True, False, False, None]
    # underscore = exactly one char (host path)
    assert _eval(S.Like(col("s"), "appl_"), b) == [True, False, False, None]


def test_trim():
    b = _batch(s=["  hi  ", "hi", "   ", None])
    assert _eval(S.StringTrim(col("s")), b) == ["hi", "hi", "", None]
    assert _eval(S.StringTrimLeft(col("s")), b) == ["hi  ", "hi", "", None]
    assert _eval(S.StringTrimRight(col("s")), b) == ["  hi", "hi", "", None]


def test_pad():
    b = _batch(s=["ab", "abcdef", None])
    assert _eval(S.StringLPad(col("s"), 4, "*"), b) == ["**ab", "abcd", None]
    assert _eval(S.StringRPad(col("s"), 4, "*"), b) == ["ab**", "abcd", None]


def test_locate():
    b = _batch(s=["foobar", "barbar", "xyz", None])
    assert _eval(S.StringLocate(lit("bar"), col("s")), b) == [4, 1, 0, None]


def test_replace():
    b = _batch(s=["aXbXc", "nope", None])
    assert _eval(S.StringReplace(col("s"), "X", "--"), b) == \
        ["a--b--c", "nope", None]


def test_regexp_extract_host():
    b = _batch(s=["a123b", "xyz", None])
    assert _eval(S.RegExpExtractHost(col("s"), r"([0-9]+)", 1), b) == \
        ["123", "", None]


def test_murmur3_matches_spark_reference_values():
    """Bit-compat check against a host reimplementation of Spark's
    Murmur3_x86_32 (hashInt/hashLong/hashUnsafeBytes, seed 42) — the algorithm
    Spark's Murmur3Hash expression and HashPartitioning use."""
    from spark_rapids_tpu.ops.hashing import Murmur3Hash
    b = ColumnarBatch.from_pydict({"i": [0, 42, -1]},
                                  schema=dt.Schema([("i", dt.INT32)]))
    out = _eval(Murmur3Hash(col("i")), b)
    assert out == [_ref_int(0), _ref_int(42), _ref_int(-1)]
    assert out == [933211791, 29417773, -1604776387]


def test_murmur3_long_and_string():
    from spark_rapids_tpu.ops.hashing import Murmur3Hash
    b = _batch(l=[0, 42], s=["", "abc"])
    out = _eval(Murmur3Hash(col("l")), b)
    assert out == [-1670924195, 1316951768]
    out_s = _eval(Murmur3Hash(col("s")), b)
    assert out_s == [_ref_bytes(b""), _ref_bytes(b"abc")]
    assert out_s == [142593372, 1322437556]


_M = 0xFFFFFFFF


def _rotl(x, r):
    return ((x << r) | (x >> (32 - r))) & _M


def _mixk1(k1):
    k1 = (k1 * 0xCC9E2D51) & _M
    return (_rotl(k1, 15) * 0x1B873593) & _M


def _mixh1(h1, k1):
    h1 ^= k1
    return (_rotl(h1, 13) * 5 + 0xE6546B64) & _M


def _fmix(h1, ln):
    h1 ^= ln
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M
    return h1 ^ (h1 >> 16)


def _s32(x):
    return x - (1 << 32) if x >= 1 << 31 else x


def _ref_int(v, seed=42):
    return _s32(_fmix(_mixh1(seed, _mixk1(v & _M)), 4))


def _ref_bytes(bs, seed=42):
    h1 = seed
    n = len(bs)
    for i in range(0, n // 4 * 4, 4):
        k1 = bs[i] | bs[i + 1] << 8 | bs[i + 2] << 16 | bs[i + 3] << 24
        h1 = _mixh1(h1, _mixk1(k1))
    for i in range(n // 4 * 4, n):
        b = bs[i] - 256 if bs[i] >= 128 else bs[i]
        h1 = _mixh1(h1, _mixk1(b & _M))
    return _s32(_fmix(h1, n))


def test_string_literal_project_fuses(caplog):
    """String literals broadcast trace-safely (static byte row + live
    mask): the whole-stage project must NOT fall back to eager."""
    import logging
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api.functions import col, lit

    s = TpuSession.builder.getOrCreate()
    df = s.createDataFrame({"v": [1.0, 2.0, 3.0]})
    with caplog.at_level(logging.WARNING, logger="spark_rapids_tpu.fusion"):
        out = df.select(lit("tag").alias("c"),
                        (col("v") * 2).alias("d")).collect()
    assert out == [("tag", 2.0), ("tag", 4.0), ("tag", 6.0)]
    s.assert_on_tpu()
    assert not [r for r in caplog.records if "fell back" in r.message], \
        [r.message for r in caplog.records]
