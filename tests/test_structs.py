"""STRUCT type: arrow<->device round trip, getField shredding, clean
fallback for whole-struct plans (ref complexTypeExtractors.scala
GetStructField; round-3 VERDICT item 10)."""

import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar import dtypes as dt

from golden import assert_tpu_and_cpu_equal


def _struct_table():
    return pa.table({
        "id": [1, 2, 3, 4],
        "s": pa.array([{"x": 1, "y": 2.5}, {"x": 3, "y": 4.5}, None,
                       {"x": 7, "y": None}],
                      type=pa.struct([("x", pa.int64()),
                                      ("y", pa.float64())])),
    })


def test_struct_dtype_roundtrip():
    t = dt.from_arrow(_struct_table().schema.field("s").type)
    assert dt.is_struct(t)
    assert t.fields == (("x", dt.INT64), ("y", dt.FLOAT64))
    assert dt.to_arrow(t) == pa.struct([("x", pa.int64()),
                                        ("y", pa.float64())])


def test_struct_collect_roundtrip():
    """Whole-struct materialization crosses the host boundary as python
    dicts (ObjectColumn path, like map<string,_>)."""
    s = TpuSession.builder.getOrCreate()
    out = s.createDataFrame(_struct_table()).collect()
    assert out == [(1, {"x": 1, "y": 2.5}), (2, {"x": 3, "y": 4.5}),
                   (3, None), (4, {"x": 7, "y": None})]
    at = s.createDataFrame(_struct_table()).to_arrow()
    assert at.column("s").to_pylist() == \
        _struct_table().column("s").to_pylist()


def test_struct_getfield_shreds_to_device():
    """getField-only queries shred struct fields into flat scan columns
    and run fully on the device (no CPU fallback)."""
    s = TpuSession.builder.getOrCreate()
    df = s.createDataFrame(_struct_table())
    out = (df.select(col("id"), col("s").getField("x").alias("x"),
                     col("s").getField("y").alias("y"))
           .filter(col("x") > 0).collect())
    assert out == [(1, 1, 2.5), (2, 3, 4.5), (4, 7, None)]
    s.assert_on_tpu()
    plan = str(s.last_plan())
    assert "CpuFallback" not in plan, plan


def test_struct_getfield_aggregate_golden():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(_struct_table())
        .groupBy(col("s").getField("x").alias("x"))
        .agg(F.count("*").alias("c")),
        approx=1e-9, ignore_order=True)


def test_struct_whole_use_falls_back_cleanly():
    """Selecting the struct itself cannot shred: the planner tags the
    plan off the device and the CPU engine produces correct rows."""
    s = TpuSession.builder.getOrCreate()
    df = s.createDataFrame(_struct_table())
    out = df.filter(col("id") <= 2).select(col("s")).collect()
    assert out == [({"x": 1, "y": 2.5},), ({"x": 3, "y": 4.5},)]


def test_nested_struct_tags_off_cleanly():
    """struct<..., struct<...>> has no shredding yet for the nested
    member: whole-plan CPU fallback with correct results."""
    inner = pa.struct([("a", pa.int64())])
    t = pa.table({
        "id": [1, 2],
        "s": pa.array([{"p": {"a": 5}}, {"p": {"a": 6}}],
                      type=pa.struct([("p", inner)])),
    })
    s = TpuSession.builder.getOrCreate()
    out = s.createDataFrame(t).collect()
    assert out == [(1, {"p": {"a": 5}}), (2, {"p": {"a": 6}})]


def test_struct_survives_join_and_collect_device_side():
    """VERDICT r4 item 10 'done' check: a whole-struct column flows
    through a shuffled join + sort + collect DEVICE-side (StructColumn:
    struct-of-columns + validity; no ObjectColumn crawl, no CPU
    fallback)."""
    from spark_rapids_tpu.columnar.column import StructColumn

    s = TpuSession.builder.config({
        "spark.rapids.tpu.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    left = s.createDataFrame(_struct_table())
    right = s.createDataFrame({"rid": [1, 2, 4, 9],
                               "w": [10.0, 20.0, 40.0, 90.0]})
    df = (left.join(right, on=(col("id") == col("rid")), how="inner")
          .orderBy(col("id").desc()))
    batch = df.collect_batch()
    si = batch.schema.names().index("s")
    assert isinstance(batch.columns[si], StructColumn), \
        type(batch.columns[si])
    assert df.collect() == [
        (4, {"x": 7, "y": None}, 4, 40.0),
        (2, {"x": 3, "y": 4.5}, 2, 20.0),
        (1, {"x": 1, "y": 2.5}, 1, 10.0)]
    s.assert_on_tpu()


def test_struct_device_getfield_no_shred():
    """GetField on a StructColumn that was NOT shredded (post-join
    projection) reads the device child directly."""
    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    df = s.createDataFrame(_struct_table())
    r = s.createDataFrame({"rid": [1, 3], "w": [1.0, 3.0]})
    out = (df.join(r, on=(col("id") == col("rid")))
           .select(col("s").getField("x").alias("sx"), col("w"))
           .collect())
    assert sorted(out, key=lambda t: t[1]) == [(1, 1.0), (None, 3.0)]


def test_struct_key_using_join_falls_back_to_cpu():
    """A using-style join (on=['col']) whose key is STRUCT-typed must hit
    the struct-key CPU-fallback guard (resolved from the child schema —
    the condition's unresolved refs carry no dtype) instead of crashing
    device kernels."""
    st = pa.struct([("x", pa.int64()), ("y", pa.float64())])
    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    left = s.createDataFrame(pa.table({
        "sk": pa.array([{"x": 1, "y": 1.5}, {"x": 2, "y": 2.5},
                        {"x": 3, "y": 3.5}], type=st),
        "v": [1, 2, 3]}))
    right = s.createDataFrame(pa.table({
        "sk": pa.array([{"x": 1, "y": 1.5}, {"x": 3, "y": 99.0}],
                       type=st),
        "w": [10, 30]}))
    df = left.join(right, on=["sk"], how="inner")
    # struct equality is whole-value: (3, 3.5) != (3, 99.0)
    assert sorted(df.collect(), key=repr) == [({"x": 1, "y": 1.5}, 1, 10)]
    with pytest.raises(AssertionError, match="ran on CPU"):
        s.assert_on_tpu()
