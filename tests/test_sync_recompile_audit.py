"""Runtime sync auditor + recompile audit (analysis/sync_audit.py,
analysis/recompile.py): per-span sync attribution, transfer-guard arming,
the q3-shaped join staying O(1) transfers per stage under span accounting,
and distinct-compile tracking with the per-batch-shape flag.
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.analysis import recompile, sync_audit
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession


def _session(**conf):
    return TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE", **conf}).getOrCreate()


# ---------------------------------------------------------------------------
# Per-span sync attribution (exec/tracing.SyncCounter + SpanRecorder)
# ---------------------------------------------------------------------------

def test_sync_report_carries_span_breakdown():
    s = _session()
    df = s.createDataFrame(pd.DataFrame(
        {"k": [1, 2, 1, 3] * 64, "v": [1., 2., 3., 4.] * 64}))
    df.groupBy("k").agg(F.sum("v").alias("sv")).collect()
    sync = s.last_query_metrics()["sync"]
    assert "syncSpans" in sync
    # every counted sync is attributed to some span bucket
    assert sum(sync["syncSpans"].values()) == sync["hostSyncs"]


def test_span_attribution_names_pipeline_resolve():
    """The batched deferred-scalar readback must be attributed to ITS span
    (pipeline_resolve), not smeared over the operator spans around it."""
    from spark_rapids_tpu.exec.pipeline import PipelineWindow
    from spark_rapids_tpu.exec.tracing import SpanRecorder, SyncCounter
    import jax.numpy as jnp
    with SyncCounter() as sc, SpanRecorder():
        win = PipelineWindow(4)
        outs = []
        for i in range(8):
            outs.extend(win.push(lambda v: v, jnp.int32(i) + 1))
        outs.extend(win.flush())
    assert outs == [1, 2, 3, 4, 5, 6, 7, 8]
    rep = sc.report()
    if rep["hostSyncs"]:                    # CPU backend may serve cached
        assert set(rep["syncSpans"]) == {"pipeline_resolve"}, rep


# ---------------------------------------------------------------------------
# q3-shaped 3-way join: O(1) transfers per stage, span-attributed
# ---------------------------------------------------------------------------

def test_q3_shaped_join_syncs_stay_o1_with_span_accounting():
    rng = np.random.default_rng(7)
    n = 8192
    line = pd.DataFrame({
        "l_order": rng.integers(0, 1000, n).astype("int64"),
        "l_price": rng.normal(100.0, 10.0, n)})
    orders = pd.DataFrame({
        "o_key": np.arange(1000, dtype="int64"),
        "o_cust": rng.integers(0, 100, 1000).astype("int64"),
        "o_date": rng.integers(0, 1000, 1000).astype("int64")})
    cust = pd.DataFrame({
        "c_key": np.arange(100, dtype="int64"),
        "c_seg": rng.integers(0, 3, 100).astype("int64")})
    s = _session(**{"spark.rapids.tpu.sql.reader.batchSizeRows": 1024})
    s.createDataFrame(line).createOrReplaceTempView("a_lineitem")
    s.createDataFrame(orders).createOrReplaceTempView("a_orders")
    s.createDataFrame(cust).createOrReplaceTempView("a_customer")
    df = s.sql(
        "SELECT l_price, o_date, c_seg FROM a_lineitem "
        "JOIN a_orders ON l_order = o_key "
        "JOIN a_customer ON o_cust = c_key "
        "WHERE o_date < 700 AND c_seg = 1")
    rows = df.collect()
    exp = (line.merge(orders, left_on="l_order", right_on="o_key")
               .merge(cust, left_on="o_cust", right_on="c_key"))
    exp = exp[(exp.o_date < 700) & (exp.c_seg == 1)]
    assert len(rows) == len(exp)
    sync = s.last_query_metrics()["sync"]
    # 8 stream batches/join stage: per-batch sizing readbacks would put
    # ~8+ syncs on the window; batched landing keeps it O(1) per stage
    resolve_syncs = sum(v for span, v in sync["syncSpans"].items()
                        if span == "pipeline_resolve")
    assert resolve_syncs <= 4, sync
    assert sum(sync["syncSpans"].values()) == sync["hostSyncs"]


# ---------------------------------------------------------------------------
# Transfer-guard arming (CPU backend: arming must at least be harmless)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["log", "disallow"])
def test_audit_modes_run_clean(mode):
    try:
        s = _session(**{"spark.rapids.tpu.sql.analysis.syncAudit": mode})
        # the session-set conf must actually reach the audit (a fresh
        # default TpuConf would read 'off' and arm nothing — vacuous)
        assert sync_audit.audit_mode() == mode
        df = s.createDataFrame(pd.DataFrame(
            {"k": [1, 2, 1], "v": [1., 2., 3.]}))
        out = df.groupBy("k").agg(F.sum("v").alias("s")).orderBy("k").collect()
        assert out == [(1, 4.0), (2, 2.0)]
    finally:
        sync_audit.reset_cache()


def test_new_session_reprimes_audit_caches():
    _session(**{"spark.rapids.tpu.sql.analysis.syncAudit": "log"})
    assert sync_audit.audit_mode() == "log"
    _session()                      # new session, default conf
    assert sync_audit.audit_mode() == "off"


def test_allowed_host_transfer_requires_reason_and_nests():
    with pytest.raises(AssertionError):
        with sync_audit.allowed_host_transfer(""):
            pass
    with sync_audit.allowed_host_transfer("test crossing"):
        pass                                   # unarmed: pure no-op


# ---------------------------------------------------------------------------
# Recompile audit
# ---------------------------------------------------------------------------

def test_repeat_query_compiles_nothing_new():
    s = _session()
    df = s.createDataFrame(pd.DataFrame(
        {"k": [1, 2, 1, 3] * 32, "v": [1., 2., 3., 4.] * 32}))

    def q():
        return df.groupBy("k").agg(F.sum("v").alias("sv")).orderBy(
            "k").collect()

    first = q()
    base = recompile.snapshot()
    assert q() == first
    growth = recompile.delta(base)
    compiles = sum(d["compiles"] for d in growth.values())
    calls = sum(d["calls"] for d in growth.values())
    assert compiles == 0, growth       # same shapes: all fused-cache hits
    assert calls > 0, growth           # ...and the cache actually served


def test_fused_stage_calls_count_executions_not_instances():
    """Every batch through a FusedStage counts as a call; otherwise
    compiles ~= calls by construction and flagged() fires spuriously."""
    s = _session(**{"spark.rapids.tpu.sql.reader.batchSizeRows": 1024})
    df = s.createDataFrame(pd.DataFrame(
        {"v": [float(i) for i in range(4096)]}))
    base = recompile.snapshot()
    df.select((F.col("v") * 2).alias("x")).collect()   # 4 batches
    d = recompile.delta(base)
    assert d["project"]["calls"] >= 4, d
    assert d["project"]["compiles"] <= 1, d
    assert not recompile.flagged(d), (d, recompile.flagged(d))


def test_flagged_detects_per_shape_compiles():
    counters = {
        "well_bucketed": {"compiles": 2, "distinctShapes": 2, "calls": 100},
        "per_shape": {"compiles": 20, "distinctShapes": 20, "calls": 22},
        # eviction churn: few distinct shapes but compiling every call
        "evicted": {"compiles": 30, "distinctShapes": 3, "calls": 32},
    }
    flags = recompile.flagged(counters)
    assert "per_shape" in flags and "evicted" in flags
    assert "well_bucketed" not in flags


def test_kernel_of_joins_string_tags():
    assert recompile.kernel_of(("concat", ("f64",), (8,), (0,), 8)) == \
        "concat"
    assert recompile.kernel_of(
        ("agg", "update", "partial", ("k",), ("b",), (), ("f64",),
         "dense", 128)) == "agg/update/partial/dense"
    assert recompile.kernel_of(42) == "anon"
