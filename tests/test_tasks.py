"""Task parallelism: concurrent partition drains gated by the TpuSemaphore,
with spill-catalog accounting that holds under re-promotion.

Reference behavior being preserved: GpuSemaphore bounds concurrent device
tasks and releases on task completion (GpuSemaphore.scala:27-161);
RapidsBufferStore re-promotes spilled buffers on acquire with accounting
(RapidsBufferStore.scala:275-301).
"""

import threading

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.exec.device import TpuSemaphore
from spark_rapids_tpu.exec.spill import (BufferCatalog, SpillableColumnarBatch,
                                         StorageTier)
from spark_rapids_tpu.exec.tasks import run_partition_tasks


def _batch(n=64, base=0):
    vals = np.arange(base, base + n, dtype=np.int64)
    schema = dt.Schema([dt.Field("x", dt.INT64)])
    return ColumnarBatch(
        schema, [Column.from_numpy(vals, dt.INT64)], n)


def test_partitions_progress_concurrently():
    """All N partition tasks must be in flight at once: each partition's
    generator blocks on a barrier that only N concurrent drains can pass."""
    n = 4
    barrier = threading.Barrier(n, timeout=30)

    def part(i):
        barrier.wait()          # deadlocks (-> Broken) if drains are serial
        yield _batch(base=i * 100)

    def drain(pid, p):
        return [b.num_rows for b in p]

    out = run_partition_tasks([part(i) for i in range(n)], drain,
                              max_workers=n)
    assert out == [[64]] * n


def test_semaphore_released_after_tasks():
    TpuSemaphore.reset()
    sem = TpuSemaphore.initialize(2)

    def drain(pid, p):
        sem.acquire_if_necessary()   # what _task_begin does mid-drain
        return sum(b.num_rows for b in p)

    parts = [iter([_batch()]) for _ in range(6)]
    out = run_partition_tasks(parts, drain, max_workers=4)
    assert out == [64] * 6
    # every permit must be back (release-on-task-completion contract)
    assert sem._sem._value == 2
    TpuSemaphore.reset()


def test_semaphore_bounds_concurrent_device_holders():
    TpuSemaphore.reset()
    sem = TpuSemaphore.initialize(2)
    holders = []
    peak = []
    lock = threading.Lock()

    def drain(pid, p):
        sem.acquire_if_necessary()
        with lock:
            holders.append(pid)
            peak.append(len(holders))
        import time
        time.sleep(0.05)
        with lock:
            holders.remove(pid)
        return pid

    run_partition_tasks([iter([_batch()]) for _ in range(6)], drain,
                        max_workers=6)
    assert max(peak) <= 2
    TpuSemaphore.reset()


def test_acquire_batch_repromotes_with_accounting(tmp_path):
    b = _batch(1 << 10)
    size = b.device_size_bytes()
    cat = BufferCatalog(device_budget=int(size * 2.5), host_budget=size * 10,
                        spill_dir=str(tmp_path))
    s1 = SpillableColumnarBatch(b, catalog=cat)
    s2 = SpillableColumnarBatch(_batch(1 << 10, base=5), catalog=cat)
    s3 = SpillableColumnarBatch(_batch(1 << 10, base=9), catalog=cat)
    # budget fits 2.5 batches -> the lowest-priority (first) spilled to host
    assert cat.device_bytes <= cat.device_budget
    assert cat.host_bytes > 0
    spilled = [s for s in (s1, s2, s3)
               if cat.buffers[s._id].tier == StorageTier.HOST]
    assert spilled
    # re-acquiring the spilled buffer promotes it back WITH accounting:
    # something else spills to make room, and the budget still holds
    got = spilled[0].get_batch()
    assert got.num_rows == 1 << 10
    assert cat.buffers[spilled[0]._id].tier == StorageTier.DEVICE
    assert cat.device_bytes <= cat.device_budget
    # total accounted device bytes equals the sum of device-tier buffers
    expect = sum(buf.size_bytes for buf in cat.buffers.values()
                 if buf.tier == StorageTier.DEVICE)
    assert cat.device_bytes == expect
    for s in (s1, s2, s3):
        s.close()
    assert cat.device_bytes == 0 and cat.host_bytes == 0


def test_collect_parallel_partitions_match_serial():
    """execute_collect over a multi-partition scan returns the same rows
    regardless of drain interleaving."""
    import pyarrow as pa
    from spark_rapids_tpu.plan.physical import TpuLocalScanExec

    table = pa.table({"x": list(range(1000))})
    schema = dt.Schema([dt.Field("x", dt.INT64)])
    exec_ = TpuLocalScanExec(table, schema, batch_rows=100, num_partitions=5)
    out = exec_.execute_collect()
    got = sorted(out.column(0).to_pylist(out.num_rows))
    assert got == list(range(1000))
