"""Process-lifetime telemetry (ISSUE 7): metrics registry + Prometheus
round trip, HBM watermark accounting with per-operator peak attribution,
the always-on flight recorder (auto-dump on task failure), the scrape
endpoint, and the registry-publish discipline (resolve boundaries, never
per row)."""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.service import telemetry as tel


def _session(**conf):
    return TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE", **conf}).getOrCreate()


def _q3_tables(s, n=4096):
    rng = np.random.default_rng(11)
    line = pd.DataFrame({
        "l_order": rng.integers(0, 500, n).astype("int64"),
        "l_price": rng.normal(100.0, 10.0, n)})
    orders = pd.DataFrame({
        "o_key": np.arange(500, dtype="int64"),
        "o_cust": rng.integers(0, 50, 500).astype("int64"),
        "o_date": rng.integers(0, 500, 500).astype("int64")})
    cust = pd.DataFrame({
        "c_key": np.arange(50, dtype="int64"),
        "c_seg": rng.integers(0, 3, 50).astype("int64")})
    s.createDataFrame(line).createOrReplaceTempView("t_lineitem")
    s.createDataFrame(orders).createOrReplaceTempView("t_orders")
    s.createDataFrame(cust).createOrReplaceTempView("t_customer")


T_Q3 = ("SELECT l_price, o_date, c_seg FROM t_lineitem "
        "JOIN t_orders ON l_order = o_key "
        "JOIN t_customer ON o_cust = c_key "
        "WHERE o_date < 350 AND c_seg = 1")


# ---------------------------------------------------------------------------
# Registry model
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram_basics():
    tel.MetricsRegistry.reset()
    reg = tel.MetricsRegistry.get()
    c = reg.counter("tpu_flight_dumps_total", "help text")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError):
        c.inc(-1)                       # counters only grow
    g = reg.gauge("tpu_hbm_bytes", "", store="device")
    g.set(100)
    g.set(40)
    assert g.value == 40
    # same name + different labels = distinct samples
    g2 = reg.gauge("tpu_hbm_bytes", "", store="host")
    g2.set(7)
    assert g.value == 40 and g2.value == 7
    h = reg.histogram("tpu_span_seconds", "", name="sort")
    h.observe(0.003)
    h.observe(0.2)
    assert h.count == 2 and abs(h.sum - 0.203) < 1e-9
    # one name cannot change kind
    with pytest.raises(ValueError):
        reg.gauge("tpu_flight_dumps_total")
    tel.MetricsRegistry.reset()


def test_prometheus_text_round_trip():
    """Parse what we emit: every sample value and label survives the
    text exposition format, histograms included (cumulative buckets +
    _sum/_count)."""
    tel.MetricsRegistry.reset()
    reg = tel.MetricsRegistry.get()
    reg._collectors = []               # no harvest: a closed fixture
    reg.counter("tpu_recompiles_total", "compile builds").inc(17)
    reg.gauge("tpu_hbm_peak_bytes", "peak", store="device").set(4096)
    reg.gauge("tpu_hbm_peak_operator_info", "", store="device",
              operator='Tpu"Weird"\nExec').set(1)
    # literal backslash-n (NOT a newline): chained-replace unescaping
    # would corrupt this into backslash+newline
    reg.gauge("tpu_backend_info", "", platform=r"c:\new\tpu").set(1)
    h = reg.histogram("tpu_span_seconds", "spans", name="join")
    for v in (0.0005, 0.004, 0.07, 2.0):
        h.observe(v)

    parsed = tel.parse_prometheus_text(reg.prometheus_text())
    assert parsed["tpu_recompiles_total"] == [({}, 17.0)]
    assert ({"store": "device"}, 4096.0) in parsed["tpu_hbm_peak_bytes"]
    # label escaping round-trips
    (labels, one), = parsed["tpu_hbm_peak_operator_info"]
    assert labels["operator"] == 'Tpu"Weird"\nExec' and one == 1.0
    (labels2, _), = parsed["tpu_backend_info"]
    assert labels2["platform"] == r"c:\new\tpu"
    # histogram: cumulative buckets end at the total count
    buckets = parsed["tpu_span_seconds_bucket"]
    assert buckets[-1][0]["le"] == "+Inf" and buckets[-1][1] == 4.0
    counts = [v for _l, v in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert parsed["tpu_span_seconds_count"][0][1] == 4.0
    assert abs(parsed["tpu_span_seconds_sum"][0][1] - 2.0745) < 1e-9
    tel.MetricsRegistry.reset()


def test_exec_bag_publishes_at_resolve_not_per_inc():
    """The registry hot-path discipline: TpuMetrics.inc never touches the
    registry; the fold happens at resolve (a reporting boundary), once,
    without double counting on later resolves."""
    from spark_rapids_tpu.exec.metrics import TpuMetrics
    tel.MetricsRegistry.reset()
    reg = tel.MetricsRegistry.get()
    bag = TpuMetrics()
    for _ in range(1000):
        bag.inc("numOutputRows", 1)
    ctr = reg.counter("tpu_exec_metric_total", key="numOutputRows")
    assert ctr.value == 0, "inc must not publish"
    bag.resolve()
    assert ctr.value == 1000
    bag.resolve()                       # idempotent: no new delta
    assert ctr.value == 1000
    bag.inc("numOutputRows", 5)
    assert dict(bag.items())["numOutputRows"] == 1005  # items() resolves
    assert ctr.value == 1005
    tel.MetricsRegistry.reset()


# ---------------------------------------------------------------------------
# Watermarks
# ---------------------------------------------------------------------------

def test_watermark_peak_monotonic_and_operator_attribution():
    from spark_rapids_tpu.exec.metrics import TpuMetrics, exec_scope
    tel.reset_watermarks()
    wm = tel.watermark("device", bag_key="peakDeviceBytes")
    bag = TpuMetrics()
    bag.owner = "TpuFakeJoinExec"
    wm.update(100)
    with exec_scope(bag):
        wm.update(5000)                 # new peak inside the exec scope
    wm.update(300)                      # current falls, peak must not
    assert wm.current == 300
    assert wm.peak == 5000
    assert wm.peak_operator == "TpuFakeJoinExec"
    assert bag.get("peakDeviceBytes") == 5000
    # a lower later "peak" never overwrites the bag watermark either
    with exec_scope(bag):
        wm.update(400)
    assert wm.peak == 5000 and bag.get("peakDeviceBytes") == 5000
    tel.reset_watermarks()


def test_q3_join_drives_device_watermark_with_attribution():
    """End to end under the q3-shaped 3-way join: batch registration in
    the spill catalog moves the device watermark, the peak is monotone
    vs current, and the peak carries an operator attribution (the open
    exec scope at registration time)."""
    tel.reset_watermarks()
    s = _session(**{"spark.rapids.tpu.sql.reader.batchSizeRows": 1024})
    _q3_tables(s)
    rows = s.sql(T_Q3).collect()
    assert rows                          # the join produced output
    wm = tel.watermarks().get("device")
    assert wm is not None and wm.peak > 0
    assert wm.peak >= wm.current
    assert wm.peak_operator and wm.peak_operator.startswith("Tpu")
    # ... and the registry exposes it (acceptance: HBM watermarks from
    # the one registry)
    snap = s.metrics_snapshot()
    fam = snap["metrics"]["tpu_hbm_peak_bytes"]
    dev = [x for x in fam["samples"] if x["labels"].get("store") == "device"]
    assert dev and dev[0]["value"] == wm.peak


def test_metrics_snapshot_exposes_all_subsystems():
    """Acceptance check: semaphore, lockdep, sync, recompile, spill,
    shuffle-transport and HBM watermark metrics from ONE registry."""
    s = _session()
    df = s.createDataFrame(pd.DataFrame(
        {"k": [1, 2, 1, 3] * 64, "v": [1.0, 2.0, 3.0, 4.0] * 64}))
    df.groupBy("k").agg(F.sum("v").alias("sv")).collect()
    _ = s.last_query_metrics()          # resolve boundary: bags publish
    names = set(s.metrics_snapshot()["metrics"])
    for want in ("tpu_semaphore_wait_seconds_total",
                 "tpu_semaphore_hold_seconds_total",
                 "tpu_lock_acquires_total",       # conftest: lockdep=record
                 "tpu_host_syncs_total",
                 "tpu_recompiles_total",
                 "tpu_spill_device_bytes",
                 "tpu_shuffle_bytes_fetched_total",
                 "tpu_hbm_bytes", "tpu_hbm_peak_bytes",
                 "tpu_exec_metric_total",
                 "tpu_span_seconds",
                 "tpu_device_budget_bytes"):
        assert want in names, f"{want} missing from the registry snapshot"
    # JSONL export appends one parseable line per call
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "sub", "metrics.jsonl")
        s.metrics_snapshot(path)
        s.metrics_snapshot(path)
        lines = open(path).read().strip().splitlines()
        assert len(lines) == 2
        assert "tpu_host_syncs_total" in json.loads(lines[0])["metrics"]


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_fixed_size_newest_win():
    r = tel.FlightRecorder(capacity=16)
    for i in range(40):
        r.record("span", f"s{i}")
    ev = r.events()
    assert len(ev) == 16
    assert ev[0]["name"] == "s24" and ev[-1]["name"] == "s39"
    assert r.event_count() == 40


def test_spans_feed_flight_ring_without_tracing_enabled():
    """The always-on property: NO tracing conf, no SpanRecorder — spans
    still land in the ring (post-mortems must not require foresight)."""
    from spark_rapids_tpu.exec.tracing import trace_span
    tel.FlightRecorder.reset()
    _session()                          # primes the flight gate
    with trace_span("always_on_probe"):
        pass
    names = [e["name"] for e in tel.FlightRecorder.get().events()
             if e["kind"] == "span"]
    assert "always_on_probe" in names


def test_flight_dump_on_injected_task_failure(tmp_path):
    """A task-body failure must produce a flight artifact WITHOUT any
    tracing pre-enabled, containing the failing span, and the original
    exception must propagate unmasked."""
    flight_dir = str(tmp_path / "flight")
    s = _session(**{
        "spark.rapids.tpu.sql.telemetry.flightRecorderDir": flight_dir})
    tel.FlightRecorder.reset()          # fresh ring for a clean assert
    df = s.createDataFrame(pd.DataFrame({"a": [1.0, 2.0, 3.0, 4.0]}))

    def boom(it):
        for _pdf in it:
            raise ValueError("injected task failure")

    from spark_rapids_tpu.columnar import dtypes as dt
    bad = df.mapInPandas(boom, dt.Schema([dt.Field("a", dt.FLOAT64)]))
    with pytest.raises(ValueError, match="injected task failure"):
        bad.collect()
    arts = sorted(os.listdir(flight_dir))
    assert arts, "no flight artifact written"
    doc = json.load(open(os.path.join(flight_dir, arts[0])))
    assert "injected task failure" in (doc["reason"] or "")
    spans = [e for e in doc["events"] if e["kind"] == "span"]
    assert spans, "artifact carries no spans"
    # the failing span is error-marked (the exception unwound through it)
    assert any(e.get("data", {}).get("error") for e in spans), spans


def test_failed_flight_dump_never_masks_query_exception(tmp_path):
    """An unwritable dump dir loses the artifact, NEVER the original
    exception (satellite: telemetry writes must not mask errors)."""
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where a directory is expected")
    s = _session(**{
        "spark.rapids.tpu.sql.telemetry.flightRecorderDir":
            str(blocker / "sub")})
    df = s.createDataFrame(pd.DataFrame({"a": [1.0, 2.0]}))

    def boom(it):
        for _pdf in it:
            raise ValueError("the real failure")

    from spark_rapids_tpu.columnar import dtypes as dt
    bad = df.mapInPandas(boom, dt.Schema([dt.Field("a", dt.FLOAT64)]))
    with pytest.raises(ValueError, match="the real failure"):
        bad.collect()


def test_session_dump_flight_record_on_demand(tmp_path):
    s = _session()
    with_path = s.dump_flight_record(str(tmp_path / "deep" / "fr.json"))
    doc = json.load(open(with_path))
    assert doc["reason"] == "on-demand"
    assert isinstance(doc["events"], list)


def test_conf_change_recorded(tmp_path):
    s = _session()
    from spark_rapids_tpu.api.session import RuntimeConf
    RuntimeConf(s).set("spark.rapids.tpu.sql.shuffle.partitions", 4)
    ev = [e for e in tel.FlightRecorder.get().events()
          if e["kind"] == "conf"]
    assert any(e["name"] == "spark.rapids.tpu.sql.shuffle.partitions"
               for e in ev)


# ---------------------------------------------------------------------------
# Scrape endpoint
# ---------------------------------------------------------------------------

def test_scrape_endpoint_serves_and_shuts_down():
    tel.stop_server()
    srv = tel.start_server(0)           # ephemeral port
    assert srv.port > 0
    base = f"http://127.0.0.1:{srv.port}"
    with urllib.request.urlopen(base + "/metrics", timeout=5) as resp:
        assert resp.status == 200
        text = resp.read().decode()
    parsed = tel.parse_prometheus_text(text)
    assert any(n.startswith("tpu_") for n in parsed)
    with urllib.request.urlopen(base + "/snapshot", timeout=5) as resp:
        snap = json.loads(resp.read().decode())
    assert "metrics" in snap
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(base + "/nope", timeout=5)
    tel.stop_server()
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(base + "/metrics", timeout=1)


# ---------------------------------------------------------------------------
# Overhead guard
# ---------------------------------------------------------------------------

def test_telemetry_overhead_within_small_factor():
    """The fused pipeline with telemetry (metrics + flight recorder) on
    stays within a coarse factor of disabled — the registry publishes at
    resolve/flush boundaries, so per-batch cost is a handful of dict
    ops, not a per-row stream. Bound is deliberately loose (2-CPU CI
    boxes under load), but a per-row publish would blow it by orders of
    magnitude."""
    import time

    data = pd.DataFrame({"k": np.arange(8192) % 37,
                         "v": np.linspace(0.0, 1.0, 8192)})

    def run_query(s):
        df = s.createDataFrame(data)
        return (df.filter(F.col("v") > 0.1)
                  .groupBy("k").agg(F.sum("v").alias("sv")).collect())

    def timed(s, iters=3):
        run_query(s)                    # warm: compile cache primed
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            run_query(s)
            best = min(best, time.perf_counter() - t0)
        return best

    off = timed(_session(**{
        "spark.rapids.tpu.sql.metrics.enabled": "false",
        "spark.rapids.tpu.sql.telemetry.flightRecorder": "false"}))
    on = timed(_session())              # defaults: both on
    assert on <= off * 8 + 0.25, (on, off)
