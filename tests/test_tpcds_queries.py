"""TPC-DS-like benchmark queries, golden-compared at tiny scale (the
tpcds_test.py analog of the reference's integration suite; BASELINE.md
milestone 2)."""

import pytest

from benchmarks import datagen, tpcds_queries as DS

from golden import assert_tpu_and_cpu_equal

_SF = 0.002


@pytest.mark.parametrize("qname", sorted(DS.TPCDS_QUERIES))
def test_tpcds_query_golden(qname):
    assert_tpu_and_cpu_equal(
        lambda s: DS.TPCDS_QUERIES[qname](
            datagen.register_tpcds_tables(s, _SF)),
        approx=1e-5, ignore_order=False)


def test_rollup_golden():
    """df.rollup grouping sets vs the CPU oracle (GpuExpandExec path)."""
    from spark_rapids_tpu.api import functions as F

    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(
            {"a": ["x", "x", "y", "y", "z"], "b": [1, 2, 1, 1, 3],
             "v": [10.0, 20.0, 30.0, 5.0, 7.5]})
        .rollup("a", "b").agg(F.sum("v").alias("sv"),
                              F.count("*").alias("c")),
        approx=1e-9, ignore_order=True)


def test_cube_golden():
    from spark_rapids_tpu.api import functions as F

    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(
            {"a": ["x", "x", "y"], "b": [1, 2, 1], "v": [1.0, 2.0, 4.0]})
        .cube("a", "b").agg(F.sum("v").alias("sv")),
        approx=1e-9, ignore_order=True)


from benchmarks import tpcxbb_queries as _XBB


@pytest.mark.parametrize("qname", sorted(_XBB.TPCXBB_QUERIES))
def test_tpcxbb_query_golden(qname):
    """TPCxBB-like suite (BASELINE milestone 3; the reference's
    TpcxbbLikeSpark analog) over the TPC-DS-like retail tables."""
    from benchmarks import tpcxbb_queries as XBB

    assert_tpu_and_cpu_equal(
        lambda s: XBB.TPCXBB_QUERIES[qname](
            datagen.register_tpcds_tables(s, _SF)),
        approx=1e-5, ignore_order=True)
