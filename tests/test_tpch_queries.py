"""TPC-H-like benchmark queries, golden-compared at tiny scale (the
tpch_test.py analog of the reference's integration suite, SURVEY.md §4)."""

import pytest

from benchmarks import datagen, queries as Q

from golden import assert_tpu_and_cpu_equal

_SF = 0.002


@pytest.mark.parametrize("qname", sorted(Q.QUERIES))
def test_tpch_query_golden(qname):
    assert_tpu_and_cpu_equal(
        lambda s: Q.QUERIES[qname](datagen.register_tables(s, _SF)),
        approx=1e-5, ignore_order=False)
