"""Shuffle transport tests: wire format, windowed chunk streaming, inflight
throttling, fault injection -> retry, and a real two-process fetch over
localhost TCP.

The mock rig mirrors the reference's RapidsShuffleTestHelper
(tests/.../shuffle/RapidsShuffleTestHelper.scala:26-187): an in-process
connection pair drives the REAL server handler and client protocol code,
with fault-injecting connection wrappers standing in for Mockito mocks.
"""

import socket
import threading

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.shuffle import wire
from spark_rapids_tpu.shuffle.transport import (Connection, ShuffleClient,
                                                ShuffleFetchError,
                                                ShuffleServer, ShuffleStore,
                                                SocketConnection)


def _batch(n=100, base=0, with_strings=False):
    cols = {"a": np.arange(base, base + n, dtype=np.int64),
            "b": np.linspace(0, 1, n)}
    b = ColumnarBatch.from_pydict({k: list(v) for k, v in cols.items()})
    if with_strings:
        b = ColumnarBatch.from_pydict({
            "a": list(cols["a"]), "s": [f"row-{i}" for i in range(n)]})
    return b


def _rows(batch):
    return sorted(batch.rows())


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def test_frame_roundtrip():
    f = wire.encode_frame(wire.META_REQ, {"shuffle_id": 3,
                                          "reduce_ids": [0, 1]}, b"xyz")
    buf = [f]

    def read_exact(n):
        out, buf[0] = buf[0][:n], buf[0][n:]
        return out

    t, h, p = wire.FrameReader(read_exact).next_frame()
    assert t == wire.META_REQ and h["shuffle_id"] == 3 and p == b"xyz"


def test_chunk_ranges_windowing():
    assert wire.chunk_ranges(0, 10) == [(0, 0)]
    assert wire.chunk_ranges(10, 10) == [(0, 10)]
    assert wire.chunk_ranges(25, 10) == [(0, 10), (10, 10), (20, 5)]
    total = 1 << 20
    rs = wire.chunk_ranges(total, 4096)
    assert sum(ln for _o, ln in rs) == total
    assert all(ln <= 4096 for _o, ln in rs)


# ---------------------------------------------------------------------------
# mock rig: in-process loopback with fault injection
# ---------------------------------------------------------------------------

class CorruptingConnection(Connection):
    """Flips one byte of server->client traffic past ``after_bytes``, once
    per shared state dict (first attempt only)."""

    def __init__(self, inner, state, after_bytes=600):
        self.inner = inner
        self.state = state
        self.after = after_bytes
        self.seen = 0

    def send(self, data):
        self.inner.send(data)

    def read_exact(self, n):
        data = self.inner.read_exact(n)
        if not self.state.get("corrupted") and self.seen + n > self.after:
            self.state["corrupted"] = True
            i = max(0, self.after - self.seen)
            if i < len(data):
                data = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
        self.seen += n
        return data

    def close(self):
        self.inner.close()


class DroppingConnection(Connection):
    """Kills the connection after N bytes read (first attempt only)."""

    def __init__(self, inner, state, after_bytes=400):
        self.inner = inner
        self.state = state
        self.after = after_bytes
        self.seen = 0

    def send(self, data):
        self.inner.send(data)

    def read_exact(self, n):
        if not self.state.get("dropped") and self.seen + n > self.after:
            self.state["dropped"] = True
            self.inner.close()
            raise ConnectionError("injected drop")
        self.seen += n
        return self.inner.read_exact(n)

    def close(self):
        self.inner.close()


def loopback_client(server: ShuffleServer, wrap=None, **kw) -> ShuffleClient:
    """Client whose every connection is an in-process socketpair served by
    the REAL server handler on a daemon thread."""

    def connect():
        a, b = socket.socketpair()
        threading.Thread(target=server.handle_connection,
                         args=(SocketConnection(b),), daemon=True).start()
        conn = SocketConnection(a)
        return wrap(conn) if wrap else conn

    return ShuffleClient(connect, **kw)


def _server_with(batches, chunk_bytes=wire.DEFAULT_CHUNK_BYTES):
    store = ShuffleStore()
    for rid, b in batches:
        store.register_batch(7, rid, b)
    return ShuffleServer(store, chunk_bytes=chunk_bytes)


def test_fetch_single_partition():
    b = _batch(500)
    srv = _server_with([(0, b)])
    got = loopback_client(srv).fetch(7, [0])
    assert len(got) == 1
    assert _rows(got[0]) == _rows(b)


def test_fetch_multi_partition_multi_chunk():
    """Small chunk size forces many windows per buffer."""
    batches = [(r, _batch(2000, base=r * 10000)) for r in range(3)]
    srv = _server_with(batches, chunk_bytes=1024)
    client = loopback_client(srv)
    got = client.fetch(7, [0, 1, 2])
    assert len(got) == 3
    all_got = sorted(r for g in got for r in g.rows())
    all_exp = sorted(r for _rid, b in batches for r in b.rows())
    assert all_got == all_exp
    assert client.metrics["chunks"] > 3      # windowing actually chunked


def test_fetch_string_columns():
    b = _batch(64, with_strings=True)
    srv = _server_with([(0, b)])
    got = loopback_client(srv).fetch(7, [0])
    assert _rows(got[0]) == _rows(b)


def test_transport_totals_symmetric_send_and_fetch():
    """The server's send-side totals (bumped at send-window completion)
    must mirror the client's fetch-side totals: over a clean loopback
    fetch, bytes_sent == bytes_fetched and chunks_sent == chunks."""
    from spark_rapids_tpu.shuffle.transport import transport_totals
    before = transport_totals()
    batches = [(r, _batch(1500, base=r * 1000)) for r in range(2)]
    srv = _server_with(batches, chunk_bytes=2048)
    client = loopback_client(srv)
    got = client.fetch(7, [0, 1])
    assert len(got) == 2
    after = transport_totals()
    sent_b = after["bytes_sent"] - before["bytes_sent"]
    fetched_b = after["bytes_fetched"] - before["bytes_fetched"]
    assert sent_b == fetched_b > 0, (sent_b, fetched_b)
    sent_c = after["chunks_sent"] - before["chunks_sent"]
    fetched_c = after["chunks"] - before["chunks"]
    assert sent_c == fetched_c > 2, (sent_c, fetched_c)


def test_inflight_throttling_tiny_window():
    """max_inflight_bytes below a single buffer still makes progress (the
    throttle always admits at least one), and many buffers complete."""
    batches = [(r, _batch(300, base=r * 1000)) for r in range(6)]
    srv = _server_with(batches, chunk_bytes=512)
    client = loopback_client(srv, max_inflight_bytes=1)
    got = client.fetch(7, list(range(6)))
    assert len(got) == 6
    all_got = sorted(r for g in got for r in g.rows())
    all_exp = sorted(r for _rid, b in batches for r in b.rows())
    assert all_got == all_exp


def test_corruption_detected_and_retried():
    b = _batch(1000)
    srv = _server_with([(0, b)], chunk_bytes=512)
    state = {}
    client = loopback_client(
        srv, wrap=lambda c: CorruptingConnection(c, state))
    got = client.fetch(7, [0])
    assert state["corrupted"], "fault was never injected"
    assert client.metrics["retries"] >= 1
    assert _rows(got[0]) == _rows(b)


def test_connection_drop_retried():
    b = _batch(1000)
    srv = _server_with([(0, b)], chunk_bytes=512)
    state = {}
    client = loopback_client(
        srv, wrap=lambda c: DroppingConnection(c, state))
    got = client.fetch(7, [0])
    assert state["dropped"]
    assert client.metrics["retries"] >= 1
    assert _rows(got[0]) == _rows(b)


def test_fetch_fails_after_exhausted_retries():
    class AlwaysDrop(Connection):
        def send(self, data):
            pass

        def read_exact(self, n):
            raise ConnectionError("dead peer")

    client = ShuffleClient(lambda: AlwaysDrop(), max_retries=2,
                           retry_backoff_s=0.001)
    with pytest.raises(ShuffleFetchError):
        client.fetch(1, [0])
    assert client.metrics["retries"] == 2


def test_unknown_buffer_errors():
    srv = _server_with([(0, _batch(10))])
    client = loopback_client(srv, max_retries=0)
    got = client.fetch(7, [5])       # empty partition: no buffers, no error
    assert got == []


# ---------------------------------------------------------------------------
# real two-process shuffle over localhost TCP
# ---------------------------------------------------------------------------

_CHILD_SERVER = r"""
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.shuffle.transport import ShuffleServer, ShuffleStore

store = ShuffleStore()
for rid in range(4):
    batch = ColumnarBatch.from_pydict({{
        "a": list(range(rid * 1000, rid * 1000 + 512)),
        "b": [float(i) * 0.5 for i in range(512)],
    }})
    store.register_batch(42, rid, batch)
srv = ShuffleServer(store, chunk_bytes=2048).start()
print(srv.port, flush=True)
import time
time.sleep(60)
"""


def test_two_process_shuffle_over_tcp(tmp_path):
    """A separate server process hosts real batches; this process fetches
    them over localhost TCP and validates every row."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # PYTHONPATH may carry a sitecustomize that pins a remote accelerator
    # platform; the child inserts the repo path itself, so scrub it — a
    # dead tunnel must not hang a CPU-only test
    env.pop("PYTHONPATH", None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD_SERVER.format(repo=repo)],
        stdout=subprocess.PIPE, env=env, text=True)
    try:
        port = int(proc.stdout.readline().strip())
        client = ShuffleClient.for_address("127.0.0.1", port)
        got = client.fetch(42, [0, 1, 2, 3])
        assert len(got) == 4
        rows = sorted(r for g in got for r in g.rows())
        exp = sorted((rid * 1000 + i, float(i) * 0.5)
                     for rid in range(4) for i in range(512))
        assert rows == exp
        assert client.metrics["bytes_fetched"] > 0
    finally:
        proc.kill()
        proc.wait()


# -- native AddressSpaceAllocator + bounce arena (ref:
# AddressSpaceAllocator.scala:22, BounceBufferManager.scala:35) --------------

import pytest as _pytest


@_pytest.mark.parametrize("force_python", [False, True])
def test_address_space_allocator(force_python):
    from spark_rapids_tpu.exec.native_alloc import AddressSpaceAllocator
    a = AddressSpaceAllocator(1000, force_python=force_python)
    o1 = a.allocate(100)
    o2 = a.allocate(200)
    o3 = a.allocate(300)
    assert (o1, o2, o3) == (0, 100, 300)
    assert a.allocated_bytes == 600
    a.free(o2)                            # hole at [100, 300)
    assert a.free_block_count == 2
    o4 = a.allocate(150)                  # first-fit into the hole
    assert o4 == 100
    a.free(o4)
    a.free(o1)
    a.free(o3)
    assert a.allocated_bytes == 0
    # full coalescing: one free block spanning everything
    assert a.free_block_count == 1
    assert a.largest_free == 1000
    assert a.allocate(1000) == 0
    assert a.allocate(1) is None          # exhausted
    assert a.allocate(0) is None
    a.close()


def test_native_allocator_is_actually_native():
    """g++ is in this image: the C++ build must succeed and load."""
    from spark_rapids_tpu.exec.native_alloc import AddressSpaceAllocator
    a = AddressSpaceAllocator(64)
    assert a.native, "expected the C++ allocator to build via g++"
    a.close()


def test_free_unallocated_offset_raises():
    from spark_rapids_tpu.exec.native_alloc import AddressSpaceAllocator
    a = AddressSpaceAllocator(64)
    if a.native:
        with pytest.raises(ValueError):
            a.free(7)
    a.close()


def test_fetch_through_bounce_arena():
    """Client staging rides the arena: windows acquire and release across a
    multi-buffer fetch."""
    batches = [(r, _batch(1000, base=r * 5000)) for r in range(4)]
    srv = _server_with(batches, chunk_bytes=2048)
    client = loopback_client(srv)
    got = client.fetch(7, [0, 1, 2, 3])
    assert len(got) == 4
    assert client.bounce.allocator.allocated_bytes == 0   # all released
    all_got = sorted(r for g in got for r in g.rows())
    all_exp = sorted(r for _rid, b in batches for r in b.rows())
    assert all_got == all_exp


def test_exchange_stage_retry_on_lost_buffers():
    """Elastic recovery (RapidsShuffleIterator.scala:28,49): losing a reduce
    partition's buffers mid-read triggers one map-stage re-execution for the
    lost partitions and the query still returns correct results."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec

    s = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.shuffle.partitions": "4",
         "spark.rapids.tpu.sql.adaptive.enabled": "false"}).getOrCreate()
    df = s.createDataFrame({"k": list(range(40)) * 5, "v": [1.0] * 200})
    agg = df.repartition(4, "k").groupBy("k").agg(F.sum("v").alias("sv"))

    orig_execute = TpuShuffleExchangeExec.execute
    state = {"sabotaged": False, "node": None}

    def sabotaging_execute(self):
        parts = orig_execute(self)
        sh = self._shuffle
        if not state["sabotaged"] and sh is not None:
            # lose partition 0's slices AFTER the map phase wrote them
            for sl in sh.slices[0]:
                sl.close()
            state["sabotaged"] = True
            state["node"] = self
        return parts

    TpuShuffleExchangeExec.execute = sabotaging_execute
    try:
        out = dict(agg.collect())
    finally:
        TpuShuffleExchangeExec.execute = orig_execute
    assert state["sabotaged"]
    assert out == {k: 5.0 for k in range(40)}
    assert state["node"].metrics.get("fetchFailedRetries", 0) >= 1
