"""Python UDF path: udf-compiler bytecode translation, pandas UDF fallback,
mapInPandas (SURVEY.md §2.9 analogs)."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col

from golden import assert_tpu_and_cpu_equal


def test_udf_compiler_translates_arithmetic():
    """Straight-line arithmetic lambdas compile to native expressions —
    NO PandasUDF appears in the plan (the udf-compiler's whole point)."""
    from spark_rapids_tpu.ops.udf_compiler import try_compile_udf
    from spark_rapids_tpu.ops import expressions as ex
    from spark_rapids_tpu.columnar import dtypes as dt

    f = lambda x, y: (x + y) * 2 - 7
    e = try_compile_udf(f, [ex.BoundReference(0, dt.FLOAT64, True),
                            ex.BoundReference(1, dt.FLOAT64, True)])
    assert e is not None
    from spark_rapids_tpu.ops.python_udf import PandasUDF
    assert not e.collect(lambda n: isinstance(n, PandasUDF))


def test_udf_compiler_translates_branches():
    """Branches now compile via CFG path reconvergence (round-4 upgrade;
    pre-CFG this was the documented fallback case)."""
    from spark_rapids_tpu.ops.udf_compiler import try_compile_udf
    from spark_rapids_tpu.ops import conditionals as co
    from spark_rapids_tpu.ops import expressions as ex
    from spark_rapids_tpu.columnar import dtypes as dt
    f = lambda x: 1 if x > 0 else -1
    out = try_compile_udf(f, [ex.BoundReference(0, dt.FLOAT64, True)])
    assert isinstance(out, co.CaseWhen)


def test_udf_compiler_rejects_loops():
    from spark_rapids_tpu.ops.udf_compiler import try_compile_udf
    from spark_rapids_tpu.ops import expressions as ex
    from spark_rapids_tpu.columnar import dtypes as dt

    def f(x):
        t = 0
        while t < 3:
            t += x
        return t
    assert try_compile_udf(f, [ex.BoundReference(0, dt.FLOAT64, True)]) \
        is None


def test_compiled_udf_golden():
    my_udf = F.udf(lambda x, y: abs(x - y) * 2.0, "double")
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(pd.DataFrame({
            "a": [1.0, -2.0, None, 4.0], "b": [0.5, 1.5, 2.5, None]}))
            .select(my_udf(col("a"), col("b")).alias("r")))

    assert_tpu_and_cpu_equal(q, approx=1e-12)
    captured["s"].assert_on_tpu()       # compiled: fully native plan


def test_closure_constant_udf():
    k = 10.0
    my_udf = F.udf(lambda x: x * k + 1, "double")
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"a": [1.0, 2.0, 3.0]})
        .select(my_udf(col("a")).alias("r")),
        approx=1e-12)


def test_untranslatable_udf_falls_back_to_pandas_path():
    """String formatting can't compile: the pandas host path answers."""
    weird = F.udf(lambda x: float(len(f"{x:.3f}")), "double")
    rows = assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"a": [1.0, 22.5]})
        .select(weird(col("a")).alias("n")),
        approx=1e-12)
    assert [r[0] for r in sorted(rows)] == [5.0, 6.0]


def test_pandas_udf_vectorized():
    @F.pandas_udf(returnType="double")
    def plus_mean(v: pd.Series) -> pd.Series:
        return v + 1.5

    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"v": [1.0, 2.0, 3.0]})
        .select(plus_mean(col("v")).alias("r")),
        approx=1e-12)


def test_map_in_pandas():
    def double_rows(frames):
        for f in frames:
            yield f.assign(v=f.v * 2)

    def q(s):
        return (s.createDataFrame({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
                .mapInPandas(double_rows, [("k", "bigint"), ("v", "double")]))

    rows = assert_tpu_and_cpu_equal(q, approx=1e-12)
    assert sorted(r[1] for r in rows) == [2.0, 4.0, 6.0]


def test_map_in_pandas_none_yield_fails_loudly():
    """A fn yielding None mid-stream must raise (the pre-telemetry
    behavior), never be read as end-of-stream and silently truncate
    the frames after it."""
    import pytest
    from spark_rapids_tpu.api.session import TpuSession

    def bad(frames):
        for f in frames:
            yield None
            yield f

    s = TpuSession.builder.getOrCreate()
    df = (s.createDataFrame({"k": [1, 2, 3]})
          .mapInPandas(bad, [("k", "bigint")]))
    with pytest.raises(TypeError):
        df.collect()


def test_rebatch_iterator_alignment():
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.ops.python_udf import rebatch_iterator
    batches = [ColumnarBatch.from_pydict({"x": list(range(i * 100, i * 100 + n))})
               for i, n in enumerate([5, 300, 7, 120, 1])]
    out = list(rebatch_iterator(iter(batches), 100))
    sizes = [b.num_rows for b in out]
    assert all(s == 100 for s in sizes[:-1])
    assert sum(sizes) == 433
    got = sorted(v for b in out for v in b.column(0).to_pylist(b.num_rows))
    exp = sorted(v for b in batches
                 for v in b.column(0).to_pylist(b.num_rows))
    assert got == exp


# -- grouped pandas execs (GpuFlatMapGroupsInPandasExec /
# GpuAggregateInPandasExec, GpuOverrides.scala:1825-1953) -------------------

def _grouped_df(s):
    return s.createDataFrame({
        "k": [1, 2, 1, 3, 2, 1], "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]})


def test_apply_in_pandas_golden():
    """df.groupBy(k).applyInPandas: per-group frame -> frame."""
    import pandas as pd
    from spark_rapids_tpu.columnar import dtypes as dt

    def center(pdf: "pd.DataFrame") -> "pd.DataFrame":
        return pd.DataFrame({"k": pdf.k, "c": pdf.v - pdf.v.mean()})

    schema = dt.Schema([dt.Field("k", dt.INT64), dt.Field("c", dt.FLOAT64)])
    assert_tpu_and_cpu_equal(
        lambda s: _grouped_df(s).groupBy("k").applyInPandas(center, schema),
        approx=1e-9, ignore_order=True)


def test_apply_in_pandas_key_arg():
    """Two-arg form: fn(key_tuple, pdf) (pyspark dispatches on arity)."""
    import pandas as pd
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.columnar import dtypes as dt

    def tag(key, pdf):
        return pd.DataFrame({"k": [key[0]], "n": [len(pdf)]})

    schema = dt.Schema([dt.Field("k", dt.INT64), dt.Field("n", dt.INT64)])
    s = TpuSession.builder.getOrCreate()
    out = sorted(_grouped_df(s).groupBy("k").applyInPandas(tag, schema)
                 .collect())
    assert out == [(1, 3), (2, 2), (3, 1)]


def test_aggregate_in_pandas_golden():
    """groupBy(k).agg(pandas_udf grouped_agg): fn(Series) -> scalar."""
    from spark_rapids_tpu.api import functions as F

    @F.pandas_udf(returnType="double", functionType="grouped_agg")
    def geo_span(v):
        return float(v.max() - v.min())

    assert_tpu_and_cpu_equal(
        lambda s: _grouped_df(s).groupBy("k").agg(
            geo_span(F.col("v")).alias("span")),
        approx=1e-9, ignore_order=True)


def test_aggregate_in_pandas_mix_rejected():
    import pytest
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession

    @F.pandas_udf(returnType="double", functionType="grouped_agg")
    def m(v):
        return float(v.mean())

    s = TpuSession.builder.getOrCreate()
    with pytest.raises(ValueError):
        _grouped_df(s).groupBy("k").agg(m(F.col("v")), F.sum("v"))


def test_grouped_pandas_on_tpu_plan():
    """The grouped pandas execs appear in the executed plan (not a CPU
    fallback of the whole query)."""
    import pandas as pd
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.columnar import dtypes as dt

    def ident(pdf):
        return pdf

    schema = dt.Schema([dt.Field("k", dt.INT64), dt.Field("v", dt.FLOAT64)])
    s = TpuSession.builder.getOrCreate()
    _grouped_df(s).groupBy("k").applyInPandas(ident, schema).collect()
    assert "FlatMapGroupsInPandas" in str(s.last_plan())


# -- udf-compiler branches (CFG reconvergence; ref CFG.scala:329,
# Instruction.scala:830, CatalystExpressionBuilder.scala:45-126) ------------

def test_udf_compiler_branches_compile_native():
    """Conditional lambdas compile to CASE WHEN — no PandasUDF in the
    plan (round-3 VERDICT item 6's done-criterion)."""
    from spark_rapids_tpu.api.session import TpuSession

    s = TpuSession.builder.getOrCreate()
    df = s.createDataFrame({"x": [-2.0, 0.0, 3.0, 7.0]})
    f = F.udf(lambda x: x * 2.0 if x > 0 else -x, returnType="double")
    out = df.select(f(col("x")).alias("y")).collect()
    assert out == [(2.0,), (0.0,), (6.0,), (14.0,)]
    plan = str(s.last_plan())
    assert "PandasUDF" not in plan and "udf" not in plan.lower().replace(
        "tpu", ""), plan


def test_udf_compiler_branch_golden():
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame(
            {"x": [-5.0, -1.0, 0.0, 2.0, 8.0, 11.0]})
        .select(F.udf(lambda x: 1.0 if x > 10 else
                      (2.0 if x > 5 else 3.0),
                      returnType="double")(col("x")).alias("b")),
        approx=1e-9)


def test_udf_compiler_short_circuit_and_early_return():
    from spark_rapids_tpu.api.session import TpuSession

    def pick(x, y):
        if x > 0 and y > 0:
            return x + y
        if x > y:
            return x
        return y

    s = TpuSession.builder.getOrCreate()
    df = s.createDataFrame({"x": [1.0, -1.0, -3.0], "y": [2.0, -2.0, 5.0]})
    f = F.udf(pick, returnType="double")
    out = df.select(f(col("x"), col("y")).alias("p")).collect()
    assert out == [(3.0,), (-1.0,), (5.0,)]
    assert "PandasUDF" not in str(s.last_plan())


def test_udf_compiler_loop_still_falls_back():
    """Loops keep the clean pandas fallback (reference contract)."""
    from spark_rapids_tpu.api.session import TpuSession

    def looped(x):
        t = 0.0
        for _ in range(3):
            t += x
        return t

    s = TpuSession.builder.getOrCreate()
    df = s.createDataFrame({"x": [1.0, 2.0]})
    out = df.select(F.udf(looped, returnType="double")(col("x"))
                    .alias("t")).collect()
    assert out == [(3.0,), (6.0,)]


def test_cogroup_apply_in_pandas_golden():
    """cogroup().applyInPandas: per-key frame pairs, union of key sets
    (GpuFlatMapCoGroupsInPandasExec analog)."""
    from spark_rapids_tpu.columnar import dtypes as dt

    def merge(l, r):
        k = l.k.iloc[0] if len(l) else r.k.iloc[0]
        return pd.DataFrame({"k": [k],
                             "lv": [float(l.v.sum()) if len(l) else 0.0],
                             "rw": [float(r.w.sum()) if len(r) else 0.0]})

    schema = dt.Schema([dt.Field("k", dt.INT64), dt.Field("lv", dt.FLOAT64),
                        dt.Field("rw", dt.FLOAT64)])

    def build(s):
        a = s.createDataFrame({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
        b = s.createDataFrame({"k": [2, 3], "w": [10.0, 20.0]})
        return a.groupBy("k").cogroup(b.groupBy("k")) \
            .applyInPandas(merge, schema)

    assert_tpu_and_cpu_equal(build, approx=1e-9, ignore_order=True)


def test_cogroup_key_arg():
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.columnar import dtypes as dt

    def tag(key, l, r):
        return pd.DataFrame({"k": [key[0]], "n": [len(l) + len(r)]})

    schema = dt.Schema([dt.Field("k", dt.INT64), dt.Field("n", dt.INT64)])
    s = TpuSession.builder.getOrCreate()
    a = s.createDataFrame({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
    b = s.createDataFrame({"k": [2, 3], "w": [10.0, 20.0]})
    out = sorted(a.groupBy("k").cogroup(b.groupBy("k"))
                 .applyInPandas(tag, schema).collect())
    assert out == [(1, 2), (2, 2), (3, 1)]


def test_cogroup_mixed_partition_counts():
    """A multi-partition left (union) + single-partition right must still
    pair every key once: both sides co-partition whenever either needs it."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.columnar import dtypes as dt

    def merge(l, r):
        k = l.k.iloc[0] if len(l) else r.k.iloc[0]
        return pd.DataFrame({"k": [k],
                             "lv": [float(l.v.sum()) if len(l) else 0.0],
                             "rw": [float(r.w.sum()) if len(r) else 0.0]})

    schema = dt.Schema([dt.Field("k", dt.INT64), dt.Field("lv", dt.FLOAT64),
                        dt.Field("rw", dt.FLOAT64)])
    s = TpuSession.builder.getOrCreate()
    a1 = s.createDataFrame({"k": [1, 2], "v": [1.0, 2.0]})
    a2 = s.createDataFrame({"k": [1, 3], "v": [4.0, 8.0]})
    left = a1.union(a2)                      # multi-partition side
    right = s.createDataFrame({"k": [2, 3], "w": [10.0, 20.0]})
    out = sorted(left.groupBy("k").cogroup(right.groupBy("k"))
                 .applyInPandas(merge, schema).collect())
    assert out == [(1, 5.0, 0.0), (2, 2.0, 10.0), (3, 8.0, 20.0)], out


def test_cogroup_key_count_mismatch_raises():
    import pytest
    from spark_rapids_tpu.api.session import TpuSession
    s = TpuSession.builder.getOrCreate()
    a = s.createDataFrame({"k": [1], "k2": [1], "v": [1.0]})
    b = s.createDataFrame({"k": [1], "w": [2.0]})
    with pytest.raises(ValueError):
        a.groupBy("k", "k2").cogroup(b.groupBy("k")).applyInPandas(
            lambda l, r: l, [("k", "bigint")])
