"""Python UDF path: udf-compiler bytecode translation, pandas UDF fallback,
mapInPandas (SURVEY.md §2.9 analogs)."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col

from golden import assert_tpu_and_cpu_equal


def test_udf_compiler_translates_arithmetic():
    """Straight-line arithmetic lambdas compile to native expressions —
    NO PandasUDF appears in the plan (the udf-compiler's whole point)."""
    from spark_rapids_tpu.ops.udf_compiler import try_compile_udf
    from spark_rapids_tpu.ops import expressions as ex
    from spark_rapids_tpu.columnar import dtypes as dt

    f = lambda x, y: (x + y) * 2 - 7
    e = try_compile_udf(f, [ex.BoundReference(0, dt.FLOAT64, True),
                            ex.BoundReference(1, dt.FLOAT64, True)])
    assert e is not None
    from spark_rapids_tpu.ops.python_udf import PandasUDF
    assert not e.collect(lambda n: isinstance(n, PandasUDF))


def test_udf_compiler_rejects_branches():
    from spark_rapids_tpu.ops.udf_compiler import try_compile_udf
    from spark_rapids_tpu.ops import expressions as ex
    from spark_rapids_tpu.columnar import dtypes as dt
    f = lambda x: 1 if x > 0 else -1
    assert try_compile_udf(f, [ex.BoundReference(0, dt.FLOAT64, True)]) \
        is None


def test_compiled_udf_golden():
    my_udf = F.udf(lambda x, y: abs(x - y) * 2.0, "double")
    captured = {}

    def q(s):
        captured["s"] = s
        return (s.createDataFrame(pd.DataFrame({
            "a": [1.0, -2.0, None, 4.0], "b": [0.5, 1.5, 2.5, None]}))
            .select(my_udf(col("a"), col("b")).alias("r")))

    assert_tpu_and_cpu_equal(q, approx=1e-12)
    captured["s"].assert_on_tpu()       # compiled: fully native plan


def test_closure_constant_udf():
    k = 10.0
    my_udf = F.udf(lambda x: x * k + 1, "double")
    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"a": [1.0, 2.0, 3.0]})
        .select(my_udf(col("a")).alias("r")),
        approx=1e-12)


def test_untranslatable_udf_falls_back_to_pandas_path():
    """String formatting can't compile: the pandas host path answers."""
    weird = F.udf(lambda x: float(len(f"{x:.3f}")), "double")
    rows = assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"a": [1.0, 22.5]})
        .select(weird(col("a")).alias("n")),
        approx=1e-12)
    assert [r[0] for r in sorted(rows)] == [5.0, 6.0]


def test_pandas_udf_vectorized():
    @F.pandas_udf(returnType="double")
    def plus_mean(v: pd.Series) -> pd.Series:
        return v + 1.5

    assert_tpu_and_cpu_equal(
        lambda s: s.createDataFrame({"v": [1.0, 2.0, 3.0]})
        .select(plus_mean(col("v")).alias("r")),
        approx=1e-12)


def test_map_in_pandas():
    def double_rows(frames):
        for f in frames:
            yield f.assign(v=f.v * 2)

    def q(s):
        return (s.createDataFrame({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
                .mapInPandas(double_rows, [("k", "bigint"), ("v", "double")]))

    rows = assert_tpu_and_cpu_equal(q, approx=1e-12)
    assert sorted(r[1] for r in rows) == [2.0, 4.0, 6.0]


def test_rebatch_iterator_alignment():
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.ops.python_udf import rebatch_iterator
    batches = [ColumnarBatch.from_pydict({"x": list(range(i * 100, i * 100 + n))})
               for i, n in enumerate([5, 300, 7, 120, 1])]
    out = list(rebatch_iterator(iter(batches), 100))
    sizes = [b.num_rows for b in out]
    assert all(s == 100 for s in sizes[:-1])
    assert sum(sizes) == 433
    got = sorted(v for b in out for v in b.column(0).to_pylist(b.num_rows))
    exp = sorted(v for b in batches
                 for v in b.column(0).to_pylist(b.num_rows))
    assert got == exp
