"""Window function tests: device kernels vs CPU window engine.

Reference analog: WindowFunctionSuite (SURVEY.md §4 ring 1).
"""

import numpy as np
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.ops import window as W
from spark_rapids_tpu.plan import logical as lp


def _win_df(session, window_exprs):
    df = session.createDataFrame({
        "p": [1, 1, 1, 2, 2, None],
        "o": [3, 1, 2, 10, 5, 7],
        "v": [10.0, 20.0, None, 40.0, 50.0, 60.0],
    })
    plan = lp.Window(df._plan, window_exprs)
    from spark_rapids_tpu.api.dataframe import DataFrame
    return DataFrame(plan, session)


def _session():
    return TpuSession.builder.config(
        "spark.rapids.tpu.sql.explain", "NONE").getOrCreate()


def _spec(partition=("p",), order=("o",), frame=None):
    from spark_rapids_tpu.ops.expressions import ColumnRef
    return W.WindowSpec(
        [ColumnRef(c) for c in partition],
        [lp.SortOrder(ColumnRef(c)) for c in order],
        frame)


def test_row_number():
    s = _session()
    df = _win_df(s, [("rn", W.WindowExpression(W.RowNumber(), _spec()))])
    rows = sorted(df.collect(), key=lambda r: (r[0] is None, r[0] or 0, r[1]))
    # partition 1 ordered by o: o=1 -> 1, o=2 -> 2, o=3 -> 3
    by_po = {(r[0], r[1]): r[3] for r in rows}
    assert by_po[(1, 1)] == 1 and by_po[(1, 2)] == 2 and by_po[(1, 3)] == 3
    assert by_po[(2, 5)] == 1 and by_po[(2, 10)] == 2
    assert by_po[(None, 7)] == 1


def test_rank_dense_rank():
    s = _session()
    df = s.createDataFrame({"p": [1, 1, 1, 1], "o": [1, 2, 2, 3]})
    plan = lp.Window(df._plan, [
        ("rk", W.WindowExpression(W.Rank(), _spec())),
        ("dr", W.WindowExpression(W.DenseRank(), _spec())),
    ])
    from spark_rapids_tpu.api.dataframe import DataFrame
    out = sorted(DataFrame(plan, s).collect())
    assert [(r[2], r[3]) for r in out] == [(1, 1), (2, 2), (2, 2), (4, 3)]


def test_lead_lag():
    s = _session()
    df = _win_df(s, [
        ("ld", W.WindowExpression(W.Lead(
            __import__("spark_rapids_tpu.ops.expressions",
                       fromlist=["ColumnRef"]).ColumnRef("v"), 1), _spec())),
        ("lg", W.WindowExpression(W.Lag(
            __import__("spark_rapids_tpu.ops.expressions",
                       fromlist=["ColumnRef"]).ColumnRef("v"), 1, -1.0),
            _spec())),
    ])
    rows = df.collect()
    by_po = {(r[0], r[1]): (r[3], r[4]) for r in rows}
    # partition 1 by o: (o=1,v=20) -> lead=v(o=2)=None, lag=default -1
    assert by_po[(1, 1)] == (None, -1.0)
    assert by_po[(1, 2)] == (10.0, 20.0)
    assert by_po[(1, 3)] == (None, None)


def test_running_and_whole_aggregates():
    s = _session()
    from spark_rapids_tpu.ops.expressions import ColumnRef
    df = _win_df(s, [
        ("run_sum", W.WindowExpression(
            lp.AggregateExpression("sum", ColumnRef("v")),
            _spec(frame=W.WindowFrame(None, 0)))),
        ("tot", W.WindowExpression(
            lp.AggregateExpression("sum", ColumnRef("v")),
            W.WindowSpec([ColumnRef("p")], [], None))),
    ])
    rows = df.collect()
    by_po = {(r[0], r[1]): (r[3], r[4]) for r in rows}
    assert by_po[(1, 1)] == (20.0, 30.0)
    assert by_po[(1, 2)] == (20.0, 30.0)  # v None at o=2: running stays 20
    assert by_po[(1, 3)] == (30.0, 30.0)
    assert by_po[(2, 5)] == (50.0, 90.0)
    assert by_po[(2, 10)] == (90.0, 90.0)


def test_window_vs_cpu_random():
    s = _session()
    from spark_rapids_tpu.ops.expressions import ColumnRef
    rng = np.random.default_rng(3)
    n = 300
    df = s.createDataFrame({
        "p": [int(x) for x in rng.integers(0, 12, n)],
        "o": [int(x) for x in rng.integers(0, 1000, n)],
        "v": [None if rng.random() < 0.1 else float(x)
              for x in rng.normal(0, 10, n)],
    })
    plan = lp.Window(df._plan, [
        ("rn", W.WindowExpression(W.RowNumber(), _spec())),
        ("rs", W.WindowExpression(
            lp.AggregateExpression("sum", ColumnRef("v")),
            _spec(frame=W.WindowFrame(None, 0)))),
        ("mx", W.WindowExpression(
            lp.AggregateExpression("max", ColumnRef("v")),
            W.WindowSpec([ColumnRef("p")], [], None))),
    ])
    from spark_rapids_tpu.api.dataframe import DataFrame
    wdf = DataFrame(plan, s)
    from spark_rapids_tpu.cpu.engine import execute as cpu_execute
    cpu = cpu_execute(wdf._analyzed())
    tpu = wdf.collect()
    cpu_rows = sorted(
        [tuple(r) for r in cpu.itertuples(index=False, name=None)])
    tpu_rows = sorted(tpu)
    assert len(cpu_rows) == len(tpu_rows)
    for cr, tr in zip(cpu_rows, tpu_rows):
        for cv, tv in zip(cr, tr):
            if isinstance(cv, float) and isinstance(tv, float):
                assert abs(cv - tv) < 1e-9
            else:
                assert cv == tv, (cr, tr)


# -- bounded frames: N PRECEDING .. M FOLLOWING (rows) + range frames --------
# (VERDICT r3 item 9; ref: GpuWindowExpression.scala:734-800)

def _golden_window(s, window_exprs, n=400, seed=5, unique_o=False):
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import DataFrame
    from spark_rapids_tpu.cpu.engine import execute as cpu_execute
    rng = np.random.default_rng(seed)
    o = (rng.permutation(n) if unique_o
         else rng.integers(0, 200, n))
    # int32 order key: the RANGE-frame scope is <=32-bit keys (the
    # reference's timestamp-days analog); row frames don't care
    df = s.createDataFrame(pa.table({
        "p": pa.array([int(x) for x in rng.integers(0, 9, n)]),
        "o": pa.array([int(x) for x in o], type=pa.int32()),
        "v": pa.array([None if rng.random() < 0.12 else float(x)
                       for x in rng.normal(0, 10, n)]),
    }))
    plan = lp.Window(df._plan, window_exprs)
    wdf = DataFrame(plan, s)
    cpu = cpu_execute(wdf._analyzed())
    tpu = wdf.collect()
    s.assert_on_tpu()
    cpu_rows = sorted(
        [tuple(r) for r in cpu.itertuples(index=False, name=None)],
        key=repr)
    tpu_rows = sorted(tpu, key=repr)
    assert len(cpu_rows) == len(tpu_rows)
    for cr, tr in zip(cpu_rows, tpu_rows):
        for cv, tv in zip(cr, tr):
            if isinstance(cv, float) and isinstance(tv, float):
                assert abs(cv - tv) < 1e-9, (cr, tr)
            else:
                assert cv == tv, (cr, tr)


@pytest.mark.parametrize("lower,upper", [
    (-2, 2), (-3, 0), (0, 3), (-1, 1), (None, 2), (-2, None), (1, 3),
    (-5, -2),
])
@pytest.mark.parametrize("op", ["sum", "count", "min", "max", "avg"])
def test_row_frames_golden(op, lower, upper):
    s = _session()
    from spark_rapids_tpu.ops.expressions import ColumnRef
    # unique order keys keep the row-frame comparison deterministic under
    # sort ties
    _golden_window(s, [
        (f"w", W.WindowExpression(
            lp.AggregateExpression(op, ColumnRef("v")),
            _spec(frame=W.WindowFrame(lower, upper)))),
    ], n=350, unique_o=True)


def test_row_frame_count_star_and_multibatch_partitions():
    s = _session()
    _golden_window(s, [
        ("c", W.WindowExpression(
            lp.AggregateExpression("count_star", None),
            _spec(frame=W.WindowFrame(-4, 4)))),
    ], n=3000, unique_o=True)


@pytest.mark.parametrize("lower,upper", [
    (-10, 10), (-20, 0), (0, 15), (None, 5), (-7, None),
])
def test_range_frames_golden(lower, upper):
    s = _session()
    from spark_rapids_tpu.ops.expressions import ColumnRef
    _golden_window(s, [
        ("rs", W.WindowExpression(
            lp.AggregateExpression("sum", ColumnRef("v")),
            _spec(frame=W.WindowFrame(lower, upper, is_range=True)))),
        ("rc", W.WindowExpression(
            lp.AggregateExpression("count", ColumnRef("v")),
            _spec(frame=W.WindowFrame(lower, upper, is_range=True)))),
    ], n=500)


def test_range_frame_desc_falls_back():
    """Descending range frames tag off to the CPU engine."""
    s = _session()
    from spark_rapids_tpu.api.dataframe import DataFrame
    from spark_rapids_tpu.ops.expressions import ColumnRef
    df = s.createDataFrame({"p": [1, 1, 2], "o": [3, 1, 2],
                            "v": [1.0, 2.0, 3.0]})
    spec = W.WindowSpec([ColumnRef("p")],
                        [lp.SortOrder(ColumnRef("o"), ascending=False)],
                        W.WindowFrame(-2, 2, is_range=True))
    plan = lp.Window(df._plan, [
        ("w", W.WindowExpression(
            lp.AggregateExpression("sum", ColumnRef("v")), spec))])
    out = DataFrame(plan, s)
    rows = out.collect()
    s.assert_on_tpu(allowed_fallbacks=["Window"])
    assert len(rows) == 3


def test_window_in_pandas_golden():
    """Grouped-agg pandas UDF over a window partition (the
    GpuWindowInPandasExec analog): one fn call per partition, broadcast
    to its rows."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.ops import window as W
    from spark_rapids_tpu.ops import expressions as ex
    from spark_rapids_tpu.plan import logical as lp
    from golden import assert_tpu_and_cpu_equal

    @F.pandas_udf(returnType="double", functionType="grouped_agg")
    def med(v):
        return float(v.median())

    def build(s):
        df = s.createDataFrame({"k": [1, 2, 1, 2, 1],
                                "v": [1.0, 2.0, 3.0, 4.0, 9.0]})
        spec = W.WindowSpec([ex.ColumnRef("k")], [])
        plan = lp.Window(df._plan, [
            ("m", W.WindowExpression(med(F.col("v")).expr, spec))])
        return df._df(plan)

    rows = assert_tpu_and_cpu_equal(build, approx=1e-9, ignore_order=True)
    got = sorted((r[0], r[2]) for r in rows)
    assert got == [(1, 3.0), (1, 3.0), (1, 3.0), (2, 3.0), (2, 3.0)]


def test_window_in_pandas_nan_stays_nan():
    """A pandas window UDF returning NaN keeps the double NaN (Spark
    semantics — not NULL) on both engines."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.ops import window as W
    from spark_rapids_tpu.ops import expressions as ex
    from spark_rapids_tpu.plan import logical as lp
    from golden import assert_tpu_and_cpu_equal

    @F.pandas_udf(returnType="double", functionType="grouped_agg")
    def med(v):
        return float(v.median())

    def build(s):
        df = s.createDataFrame({"k": [1, 1, 2, 2],
                                "v": [None, None, 4.0, 6.0]})
        spec = W.WindowSpec([ex.ColumnRef("k")], [])
        plan = lp.Window(df._plan, [
            ("m", W.WindowExpression(med(F.col("v")).expr, spec))])
        return df._df(plan)

    assert_tpu_and_cpu_equal(build, approx=1e-9, ignore_order=True)


def test_default_rows_frame_warns_on_ordered_spec():
    """An ordered spec without an explicit frame applies the implicit ROWS
    default — documented DefaultRowsFrameWarning (Spark's default is the
    peer-inclusive RANGE form, which differs on tied order keys). Standard
    warnings filters apply, so an 'error'/'always' audit sees every
    implicit-frame call site."""
    import warnings as _warnings

    from spark_rapids_tpu.api.window import (DefaultRowsFrameWarning,
                                             Window)

    with pytest.warns(DefaultRowsFrameWarning):
        Window.partitionBy("k").orderBy("v")._to_spec()
    # user-controlled escalation works (no hand-rolled once-flag eats it)
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        with pytest.raises(DefaultRowsFrameWarning):
            Window.partitionBy("k").orderBy("v")._to_spec()
        # explicit frames / unordered specs never warn
        Window.partitionBy("k").orderBy("v").rowsBetween(
            Window.unboundedPreceding, Window.currentRow)._to_spec()
        Window.partitionBy("k")._to_spec()
