"""AQE-parity suite (ISSUE 16 acceptance): every TPC-H/TPC-DS bench
plan runs with ``spark.rapids.tpu.sql.adaptive.enabled`` ON vs OFF and
must produce identical results — the runtime re-planner (plan/aqe.py)
may only change HOW stages execute (partition grouping, skew splits,
join strategy), never what they compute.

Named ``test_zz_*`` so it runs after the golden suites have warmed the
process-global fused cache at the same scale (the assertions do not
depend on the warmth — a cold run just pays the compiles twice)."""

import math

import pytest

from benchmarks import datagen, queries as Q, tpcds_queries as DS

_SF = 0.002

_CASES = ([("tpch", n) for n in sorted(Q.QUERIES)] +
          [("tpcds", n) for n in sorted(DS.TPCDS_QUERIES)])


@pytest.fixture(scope="module")
def corpus():
    from spark_rapids_tpu.api.session import TpuSession
    session = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    return session, {"tpch": datagen.register_tables(session, _SF),
                     "tpcds": datagen.register_tpcds_tables(session, _SF)}


def _cells_equal(a, b) -> bool:
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) and math.isnan(b):
            return True
        return abs(a - b) <= 1e-6 * max(1.0, abs(a), abs(b))
    return a == b


@pytest.mark.parametrize("suite,qname", _CASES,
                         ids=[f"{s}/{n}" for s, n in _CASES])
def test_adaptive_on_off_parity(corpus, suite, qname):
    session, tables = corpus
    qfn = Q.QUERIES[qname] if suite == "tpch" else DS.TPCDS_QUERIES[qname]
    on = qfn(tables[suite]).collect_batch().fetch_to_host().rows()
    session.conf.set("spark.rapids.tpu.sql.adaptive.enabled", "false")
    try:
        off = qfn(tables[suite]).collect_batch().fetch_to_host().rows()
    finally:
        session.conf.set("spark.rapids.tpu.sql.adaptive.enabled", "true")
    assert len(on) == len(off), (len(on), len(off))
    # row order is part of parity for ordered queries; float cells compare
    # to aggregation tolerance (a coalesced/split stage may legally change
    # float reduction order at ~1e-7 rel)
    for i, (ra, rb) in enumerate(zip(on, off)):
        assert len(ra) == len(rb) and all(
            _cells_equal(a, b) for a, b in zip(ra, rb)), (i, ra, rb)
