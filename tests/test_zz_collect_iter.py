"""Streaming collect (``DataFrame.collect_iter``, ISSUE 17) parity with
``collect`` over the full TPC-H/TPC-DS bench corpus, plus early-close
resource release.

Named ``test_zz_*`` so it runs LAST in the alphabetical tier-1 order:
by then the golden suites have executed every corpus query at the same
scale, the process-global fused cache is warm, and each sweep execution
here measures the iterator protocol — not compile wall. The assertions
do NOT depend on that warmth."""

import threading

import numpy as np
import pytest

from benchmarks import datagen, queries as Q, tpcds_queries as DS

_SF = 0.002


def _session(extra=None):
    from spark_rapids_tpu.api.session import TpuSession
    conf = {"spark.rapids.tpu.sql.explain": "NONE"}
    conf.update(extra or {})
    return TpuSession.builder.config(conf).getOrCreate()


def _corpus(session):
    tpch = datagen.register_tables(session, _SF)
    tpcds = datagen.register_tpcds_tables(session, _SF)
    for name in sorted(Q.QUERIES):
        yield f"tpch/{name}", Q.QUERIES[name], tpch
    for name in sorted(DS.TPCDS_QUERIES):
        yield f"tpcds/{name}", DS.TPCDS_QUERIES[name], tpcds


def _rows_equal(a, b) -> bool:
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if not np.isclose(va, vb, rtol=1e-9, atol=1e-12,
                                  equal_nan=True):
                    return False
            elif va != vb:
                return False
    return True


def test_collect_iter_matches_collect_over_bench_corpus():
    """Every bench query returns the SAME rows in the SAME order whether
    materialized in one call or streamed batch-by-batch — the streaming
    path reorders nothing, drops nothing, duplicates nothing, and the
    session meters a first-row wall for each streamed run."""
    session = _session()
    mismatched = {}
    no_first_row = []
    for name, qfn, tables in _corpus(session):
        oracle = qfn(tables).collect()
        streamed = [r for b in qfn(tables).collect_iter()
                    for r in b.rows()]
        if not _rows_equal(streamed, oracle):
            mismatched[name] = (len(streamed), len(oracle))
        if oracle and getattr(session, "_last_first_row_s", 0.0) <= 0.0:
            no_first_row.append(name)
    assert not mismatched, (
        "collect_iter diverged from collect (streamed rows, oracle "
        f"rows): {mismatched}")
    assert not no_first_row, (
        f"streamed queries with no firstRowS metered: {no_first_row}")


def test_collect_iter_early_close_releases_resources(tmp_path):
    """Abandoning a half-consumed stream (LIMIT-style early exit, a
    client disconnect) must hand back every staging-arena window and
    leave no drain thread behind — a leak here permanently shrinks the
    process-global arena (io/scan._StagingTracker)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.io import scan as scan_mod
    rng = np.random.default_rng(17)
    for i in range(6):
        tbl = pa.table({"x": rng.integers(0, 100, 20_000),
                        "y": rng.normal(0, 1, 20_000)})
        pq.write_table(tbl, str(tmp_path / f"f{i}.parquet"))
    session = _session({
        "spark.rapids.tpu.sql.format.parquet.reader.type":
            "MULTITHREADED"})
    from spark_rapids_tpu.api.functions import col, lit
    df = (session.read.parquet(str(tmp_path))
          .filter(col("y") > lit(0.0))
          .select((col("x") * lit(2)).alias("x2"), col("y")))
    it = df.collect_iter()
    first = next(it)                # one batch crosses the stream...
    assert len(first.rows()) > 0
    it.close()                      # ...then the consumer walks away
    staging = scan_mod._STAGING
    if staging is not None:         # arena was used: must be fully freed
        assert staging.allocator.allocated_bytes == 0, \
            staging.allocator.allocated_bytes
    # close() joins the drain pool (tasks.stream_partition_tasks does
    # shutdown(wait=True) in its finally): no task worker survives it
    deadline = 50
    while deadline and any(t.name.startswith("tpu-task")
                           for t in threading.enumerate()):
        threading.Event().wait(0.1)
        deadline -= 1
    leftover = [t.name for t in threading.enumerate()
                if t.name.startswith("tpu-task")]
    assert not leftover, leftover
