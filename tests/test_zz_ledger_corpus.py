"""Corpus leak audit (ISSUE 19 acceptance): every TPC-H/TPC-DS bench
plan runs under ``spark.rapids.tpu.sql.analysis.bufferLedger=enforce``
and must finish leak-free — a device buffer minted by the query and
still catalog-resident past collect end raises
:class:`~spark_rapids_tpu.analysis.ledger.BufferLeakError` inside the
collect, which IS the assertion. Use-after-free and use-after-donate
also raise at their access sites here, so the whole corpus doubles as
a runtime exercise of the donation/spill/staging hand-off discipline.

Named ``test_zz_*`` so it runs after the golden suites have warmed the
process-global fused cache at the same scale (warmth only saves
compiles — the audit is per-query and cache-independent)."""

import pytest

from benchmarks import datagen, queries as Q, tpcds_queries as DS
from spark_rapids_tpu.analysis import ledger

_SF = 0.002

_CASES = ([("tpch", n) for n in sorted(Q.QUERIES)] +
          [("tpcds", n) for n in sorted(DS.TPCDS_QUERIES)])


@pytest.fixture(scope="module")
def corpus():
    from spark_rapids_tpu.api.session import TpuSession
    session = TpuSession.builder.config({
        "spark.rapids.tpu.sql.explain": "NONE",
        "spark.rapids.tpu.sql.analysis.bufferLedger": "enforce",
    }).getOrCreate()
    assert ledger.mode() == "enforce"
    yield session, {"tpch": datagen.register_tables(session, _SF),
                    "tpcds": datagen.register_tpcds_tables(session, _SF)}
    # back to the suite-wide record default (conftest env conf)
    ledger.install("record")


@pytest.mark.parametrize("suite,qname", _CASES,
                         ids=[f"{s}/{n}" for s, n in _CASES])
def test_corpus_leak_free_under_enforce(corpus, suite, qname):
    session, tables = corpus
    qfn = Q.QUERIES[qname] if suite == "tpch" else DS.TPCDS_QUERIES[qname]
    # enforce mode: a leak raises BufferLeakError from inside collect
    rows = qfn(tables[suite]).collect_batch().fetch_to_host().rows()
    assert rows is not None
    led = session._last_ledger
    assert led is not None, "end-of-query audit must run under enforce"
    assert led["leakedBuffers"] == 0, led
