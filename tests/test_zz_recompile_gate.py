"""Tier-1 recompile gate over the full TPC-H/TPC-DS bench plan corpus
(ISSUE 10 acceptance: ``recompileFlags`` promoted from bench-report
advisory to a tier-1 gate; docs/compile.md §3).

Named ``test_zz_*`` so it runs LAST in the alphabetical tier-1 order:
by then the golden suites (test_tpch_queries / test_tpcds_queries) have
executed every corpus query once at the same scale, so the process-
global fused cache is warm and each gate execution here is cheap. The
assertions do NOT depend on that warmth — a cold first run merely
re-seeds the cache; the invariant checked is that the back-to-back
REPEAT of each query compiles NOTHING (the repeat-traffic discipline
the whole bucket/cache design exists for) and that no query's delta
trips ``recompile.flagged``."""

import json

import pytest

from benchmarks import datagen, queries as Q, tpcds_queries as DS

_SF = 0.002


def _corpus(session):
    tpch = datagen.register_tables(session, _SF)
    tpcds = datagen.register_tpcds_tables(session, _SF)
    for name in sorted(Q.QUERIES):
        yield f"tpch/{name}", Q.QUERIES[name], tpch
    for name in sorted(DS.TPCDS_QUERIES):
        yield f"tpcds/{name}", DS.TPCDS_QUERIES[name], tpcds


def test_recompile_flags_clean_over_bench_corpus():
    from spark_rapids_tpu.analysis import recompile
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec import compile_cache
    session = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    repeat_offenders = {}
    flagged = {}

    def run_pair(qfn, tables):
        relief0 = compile_cache.relief_count()
        pair0 = recompile.snapshot()
        qfn(tables).collect_batch().fetch_to_host()  # may re-seed cache
        snap = recompile.snapshot()
        qfn(tables).collect_batch().fetch_to_host()  # the repeat
        rd = recompile.delta(snap)
        bad = {k: v for k, v in rd.items() if v.get("compiles")}
        flags = recompile.flagged(recompile.delta(pair0))
        # a JIT map-pressure relief landing INSIDE the pair legitimately
        # rebuilds programs between the two runs — not a discipline
        # violation; the caller retries once on a quiet window
        relieved = compile_cache.relief_count() != relief0
        return bad, flags, relieved

    for name, qfn, tables in _corpus(session):
        bad, flags, relieved = run_pair(qfn, tables)
        if (bad or flags) and relieved:
            bad, flags, _ = run_pair(qfn, tables)
        if bad:
            repeat_offenders[name] = bad
        if flags:
            flagged[name] = flags
    assert not repeat_offenders, (
        "repeat-query compiles over the bench corpus (a repeated shape "
        "must hit the fused cache):\n" +
        json.dumps(repeat_offenders, indent=1, default=str))
    assert not flagged, (
        "recompileFlags non-empty over the bench corpus:\n" +
        json.dumps(flagged, indent=1))


def test_stage_programs_ride_the_compile_audit_funnel():
    """ISSUE 11: whole-stage programs (plan/stage_compiler) classify
    cold-build vs disk-hit through exec/compile_cache like every other
    kernel family, and a repeat run of the same chain compiles nothing.
    (The corpus gate above already runs the 60 bench plans with
    ``fusion.wholeStage`` at its default ON — this pins the stage family
    explicitly.)"""
    import numpy as np
    from spark_rapids_tpu.analysis import recompile
    from spark_rapids_tpu.api.functions import col, lit
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec import compile_cache
    session = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    rng = np.random.default_rng(97)
    df = session.createDataFrame({
        "a": [float(x) for x in rng.normal(0, 10, 4096)],
        "b": [int(x) for x in rng.integers(0, 100, 4096)]})
    # literals unique to this test: the process-global fused cache must
    # not already hold the chain
    q = (df.select((col("a") * lit(7.03125)).alias("x"), col("b"))
         .filter(col("x") > lit(0.15625))
         .select((col("x") - col("b")).alias("y"), col("b"))
         .filter(col("b") != lit(63)))
    base = recompile.snapshot()
    q.collect_batch().fetch_to_host()
    d = recompile.delta(base)
    stage = {k: v for k, v in d.items() if k.startswith("stage")}
    assert stage, d
    (_fam, ent), = stage.items()
    assert ent["compiles"] == 1, ent
    # classified through the persistent-cache funnel: exactly one of
    # cold-build / disk-hit, with first-call wall seconds metered
    assert ent["coldCompiles"] + ent["diskHits"] == 1, ent
    assert ent["compileS"] >= 0.0
    # the signature was recorded in the persistent index: a second
    # process (or this one after an eviction) would classify 'disk'
    # when a cache dir is configured, 'cold' otherwise — classify() is
    # deterministic per key either way
    snap = recompile.snapshot()
    q.collect_batch().fetch_to_host()
    rd = recompile.delta(snap)
    assert not any(v.get("compiles") for v in rd.values()), rd


def test_size_class_discipline_clean_over_corpus():
    """After the whole suite (and the corpus gate above) every compiled
    signature in the process traces back to bucketed dimensions only —
    no string width, group bucket, or frame size leaked past the
    power-of-two size classes."""
    from spark_rapids_tpu.analysis import recompile
    leaks = recompile.size_class_report()
    assert leaks == {}, (
        "un-bucketed dimensions reached compiled signatures:\n" +
        json.dumps(leaks, indent=1))
