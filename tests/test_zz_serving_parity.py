"""Serving-parity suite + repeat-execute gate (ISSUE 12 acceptance).

Every TPC-H/TPC-DS bench plan runs through the prepared
(plan-once/execute-many) path and must produce results identical to the
direct first execution — a cached exec tree re-executed after a
parameter rebind may only change how the plan was OBTAINED, never what
it computes. The gate half pins the serving contract on q6: executing
twice with different date-range literals performs exactly one
parse/analyze/optimize/validate pass and compiles NOTHING on the second
execution, and an exact repeat short-circuits at the result cache.

Named ``test_zz_*`` so it runs after the golden suites have warmed the
process-global fused cache at this scale."""

import math

import pytest

from benchmarks import datagen, queries as Q, tpcds_queries as DS

_SF = 0.002

_CASES = ([("tpch", n) for n in sorted(Q.QUERIES)] +
          [("tpcds", n) for n in sorted(DS.TPCDS_QUERIES)])


@pytest.fixture(scope="module")
def corpus():
    from spark_rapids_tpu.api.session import TpuSession
    session = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    return session, {"tpch": datagen.register_tables(session, _SF),
                     "tpcds": datagen.register_tpcds_tables(session, _SF)}


def _cells_equal(a, b) -> bool:
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) and math.isnan(b):
            return True
        return abs(a - b) <= 1e-6 * max(1.0, abs(a), abs(b))
    return a == b


def _rows_equal(on, off):
    assert len(on) == len(off), (len(on), len(off))
    for i, (ra, rb) in enumerate(zip(on, off)):
        assert len(ra) == len(rb) and all(
            _cells_equal(a, b) for a, b in zip(ra, rb)), (i, ra, rb)


@pytest.mark.parametrize("suite,qname", _CASES,
                         ids=[f"{s}/{n}" for s, n in _CASES])
def test_prepared_vs_direct_parity(corpus, suite, qname):
    """direct execution == prepared execute == prepared RE-execute (the
    cached-tree re-execution that serving traffic lives on)."""
    session, tables = corpus
    qfn = Q.QUERIES[qname] if suite == "tpch" else DS.TPCDS_QUERIES[qname]
    direct = qfn(tables[suite]).collect_batch().fetch_to_host().rows()
    stmt = session.prepare(qfn(tables[suite]))
    _rows_equal(direct, stmt.execute().fetch_to_host().rows())
    _rows_equal(direct, stmt.execute().fetch_to_host().rows())


def _q6_sql_dates(session, tables, lo, hi):
    from spark_rapids_tpu.api.functions import col, lit
    import spark_rapids_tpu.api.functions as F
    l = tables["lineitem"]
    return (l.filter((col("l_shipdate") >= lit(lo)) &
                     (col("l_shipdate") < lit(hi)) &
                     (col("l_discount") >= lit(0.05)) &
                     (col("l_discount") <= lit(0.07)) &
                     (col("l_quantity") < lit(24)))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def test_repeat_execute_gate_q6(corpus):
    """The ISSUE 12 acceptance pin: q6 twice with different date-range
    literals = ONE parse/analyze/optimize/validate pass, ZERO cold or
    in-memory compiles on the second execution, >= 1 plan-cache hit."""
    import datetime
    from spark_rapids_tpu.analysis import recompile
    from spark_rapids_tpu.api.session import TpuSession
    session = TpuSession.builder.config(
        {"spark.rapids.tpu.sql.explain": "NONE"}).getOrCreate()
    tables = datagen.register_tables(session, _SF)
    tables["lineitem"].createOrReplaceTempView("gate_lineitem")
    stmt = session.prepare(
        "SELECT sum(l_extendedprice * l_discount) AS revenue "
        "FROM gate_lineitem "
        "WHERE l_shipdate >= :lo AND l_shipdate < :hi "
        "AND l_discount >= 0.05 AND l_discount <= 0.07 "
        "AND l_quantity < 24")
    r94 = stmt.execute(lo=datetime.date(1994, 1, 1),
                       hi=datetime.date(1995, 1, 1)).rows()
    snap = recompile.snapshot()
    r95 = stmt.execute(lo=datetime.date(1995, 1, 1),
                       hi=datetime.date(1996, 1, 1)).rows()
    # ZERO cold or in-memory compiles on the literal-changed repeat
    bad = {k: v for k, v in recompile.delta(snap).items()
           if v.get("compiles")}
    assert not bad, bad
    st = session.serving_stats()
    assert st["parses"] == 1, st          # one parse pass
    assert st["analyzes"] == 1, st        # one analyze pass
    assert st["plansBuilt"] == 1, st      # one optimize/validate pass
    assert st["planHits"] >= 1, st        # served from the plan cache
    # the values really steered the result
    assert r94 != r95, (r94, r95)
    # oracle: the dataframe q6 with the same ranges agrees
    d94 = (datetime.date(1994, 1, 1) - datetime.date(1970, 1, 1)).days
    d95 = (datetime.date(1995, 1, 1) - datetime.date(1970, 1, 1)).days
    d96 = (datetime.date(1996, 1, 1) - datetime.date(1970, 1, 1)).days
    _rows_equal(r94, _q6_sql_dates(session, tables, d94, d95)
                .collect_batch().fetch_to_host().rows())
    _rows_equal(r95, _q6_sql_dates(session, tables, d95, d96)
                .collect_batch().fetch_to_host().rows())


def test_exact_repeat_short_circuits_at_result_cache(corpus):
    import datetime
    from spark_rapids_tpu.api.session import TpuSession
    session = TpuSession.builder.config({
        "spark.rapids.tpu.sql.explain": "NONE",
        "spark.rapids.tpu.sql.resultCache.enabled": "true"}).getOrCreate()
    tables = datagen.register_tables(session, _SF)
    d94 = (datetime.date(1994, 1, 1) - datetime.date(1970, 1, 1)).days
    d95 = (datetime.date(1995, 1, 1) - datetime.date(1970, 1, 1)).days
    q = _q6_sql_dates(session, tables, d94, d95)
    r1 = q.collect_batch().fetch_to_host().rows()
    r2 = q.collect_batch().fetch_to_host().rows()
    _rows_equal(r1, r2)
    st = session.serving_stats()
    assert st["resultHits"] == 1 and st["resultStores"] >= 1, st
    assert "resultCache=hit" in session.explain_analyze()
