"""api_validation: diff this framework's registered surface against the
reference's component inventory.

Reference: ``api_validation/.../ApiValidation.scala:65-167`` diffs Gpu exec
constructor signatures against Spark's per version. Standalone analog: walk
the live registries (expression rules, exec conversions, conf keys) and
report the covered surface plus any rule whose class no longer exists or
whose conversion is missing — the drift this tool guards against.

Usage: python -m tools.api_validation [--json]
"""

from __future__ import annotations

import json
import sys


def validate() -> dict:
    from spark_rapids_tpu.plan import overrides as ov
    from spark_rapids_tpu.plan import logical as lp
    from spark_rapids_tpu import config as cfg

    report: dict = {"problems": []}

    # expression rules: every registered class must be constructible and
    # carry the eval/plan contract
    exprs = []
    for klass, rule in ov._EXPR_RULES.items():
        entry = {"class": klass.__name__,
                 "conf_key": rule.conf_key,
                 "incompat": rule.incompat}
        if not hasattr(klass, "eval"):
            report["problems"].append(
                f"expression rule {klass.__name__} has no eval")
        exprs.append(entry)
    report["expressions"] = sorted(exprs, key=lambda e: e["class"])

    # exec rules: every logical node named in EXEC_NAMES must convert
    execs = []
    convertible = set()
    import inspect
    src = inspect.getsource(ov.Overrides)
    for klass, name in ov.PlanMeta.EXEC_NAMES.items():
        has_branch = f"lp.{klass.__name__}" in src
        execs.append({"logical": klass.__name__, "exec": name,
                      "converts": has_branch})
        if not has_branch:
            report["problems"].append(
                f"exec {name} ({klass.__name__}) has no conversion branch")
    report["execs"] = sorted(execs, key=lambda e: e["exec"])

    # conf registry: keys must be unique and documented
    keys = [e.key for e in cfg.REGISTRY.entries()]
    if len(keys) != len(set(keys)):
        report["problems"].append("duplicate conf keys")
    undocumented = [e.key for e in cfg.REGISTRY.entries() if not e.doc]
    if undocumented:
        report["problems"].append(f"undocumented confs: {undocumented}")
    report["conf_keys"] = len(keys)

    report["n_expressions"] = len(exprs)
    report["n_execs"] = len(execs)
    report["ok"] = not report["problems"]
    return report


def main() -> int:
    report = validate()
    if "--json" in sys.argv:
        print(json.dumps(report, indent=2))
    else:
        print(f"expressions: {report['n_expressions']}")
        print(f"execs:       {report['n_execs']}")
        print(f"conf keys:   {report['conf_keys']}")
        for p in report["problems"]:
            print(f"PROBLEM: {p}")
        print("OK" if report["ok"] else "FAILED")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
