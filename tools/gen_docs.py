"""Regenerate docs/configs.md and docs/supported_ops.md from the live
registries (the reference generates docs/configs.md from RapidsConf.confHelp,
RapidsConf.scala:133-168, and docs/supported_ops.md from its rule registry).

Run: python tools/gen_docs.py            # rewrite the docs in place
     python tools/gen_docs.py --check    # exit 1 if the docs are stale

``--check`` is the doc-drift gate tier-1 runs (tests/test_static_analysis.py
invokes it in a FRESH subprocess so dynamically-registered per-operator conf
keys from earlier queries cannot leak into the comparison).
"""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def generate() -> dict:
    """Build every generated doc as {relative path: content}."""
    from spark_rapids_tpu.config import REGISTRY
    from spark_rapids_tpu.plan.overrides import _EXPR_RULES, PlanMeta

    out = {"docs/configs.md": REGISTRY.help_text()}

    lines = [
        "# Supported operators and expressions",
        "",
        "Generated from the live replacement-rule registry "
        "(`plan/overrides.py`), the analog of the reference's generated "
        "`docs/supported_ops.md`. Every entry has an auto-generated "
        "enable/disable conf key.",
        "",
        "## Execs",
        "",
        "| Logical operator | TPU exec | Conf key |",
        "|---|---|---|",
    ]
    for lp_cls, exec_name in sorted(PlanMeta.EXEC_NAMES.items(),
                                    key=lambda kv: kv[1]):
        lines.append(
            f"| {lp_cls.__name__} | Tpu{exec_name} | "
            f"spark.rapids.tpu.sql.exec.{exec_name} |")
    lines += [
        "",
        "## Expressions",
        "",
        "| Expression | Notes | Conf key |",
        "|---|---|---|",
    ]
    for klass, rule in sorted(_EXPR_RULES.items(), key=lambda kv: kv[0].__name__):
        notes = []
        if rule.incompat:
            notes.append(f"incompat: {rule.incompat}")
        if rule.disabled_reason:
            notes.append(f"disabled: {rule.disabled_reason}")
        lines.append(f"| {klass.__name__} | {'; '.join(notes) or '—'} | "
                     f"{rule.conf_key} |")
    lines += [
        "",
        "## Known semantic deviations",
        "",
        "User-facing behavior differences from Spark (device and the CPU "
        "oracle agree with each other, not with Spark, on these inputs):",
        "",
        "- `CreateMap` with a NULL key yields a NULL map; Spark raises "
        "`RuntimeException` (null as map key).",
        "- `element_at(map, k)` with `k` absent yields NULL (matches "
        "Spark); `element_at(array, 0)` yields NULL where Spark raises "
        "an invalid-index error.",
        "- `MapValues` renders NULL map values as NULL entries in the "
        "result array only when the element type is nullable on host; "
        "device arrays cannot hold NULL elements, so NULL values read "
        "back as 0 on the device path.",
        "- `persist(storageLevel)` accepts and ignores the storage level "
        "(the spill tiers decide residency; `cache()` semantics).",
        "- Maps with string keys or values, `array<string>`, and nested "
        "complex types run on the CPU engine only (planner-tagged off "
        "the device).",
    ]
    out["docs/supported_ops.md"] = "\n".join(lines) + "\n"
    return out


def main() -> int:
    check = "--check" in sys.argv
    docs = generate()
    stale = []
    for rel, content in docs.items():
        path = os.path.join(ROOT, rel)
        if check:
            with open(path) as f:
                if f.read() != content:
                    stale.append(rel)
            continue
        with open(path, "w") as f:
            f.write(content)
    if check:
        if stale:
            print("STALE generated docs: " + ", ".join(stale) +
                  " (run: python tools/gen_docs.py)")
            return 1
        print("generated docs up to date")
        return 0
    print("regenerated " + " and ".join(docs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
