"""Project linter entry point: ``python -m tools.lint [package_dir] [--json]``.

Thin wrapper over :mod:`spark_rapids_tpu.analysis.lint` (the AST rules live
there so the analyzer's own tests import them directly); exits non-zero on
any violation. See docs/analysis.md for the rules and the pragma format.
"""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from spark_rapids_tpu.analysis.lint import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
