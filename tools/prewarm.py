"""Prewarm a compile-cache directory from the CLI.

Boots a session against ``--cache-dir``, replays the hottest fused-stage
signatures recorded in the prewarm corpus (``prewarm_corpus.jsonl``,
written beside the signature index by every cold stage build —
exec/compile_pool.py) onto the background compile pool, waits for the
builds, and prints the pool stats. Run it before traffic arrives — a
following process (``benchmarks/runner.py --prewarm``, a service boot
with ``compile.prewarm.enabled``) then serves first queries with zero
query-triggered cold compiles (docs/compile.md §5)::

    python -m tools.prewarm --cache-dir /var/cache/tpu-compile
    python -m tools.prewarm --cache-dir ./cache --top-n 8 --timeout 60

Exit code 0 when every submitted prewarm build landed, 1 otherwise
(a failed build, a drain timeout, or no corpus to replay).
"""

from __future__ import annotations

import argparse
import json
import sys


def run_prewarm(cache_dir: str, top_n: int = 32,
                timeout_s: float = 120.0) -> dict:
    """Boot, prewarm, drain; return the summary dict the CLI prints."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.exec import compile_pool

    session = TpuSession.builder.config(
        "spark.rapids.tpu.sql.explain", "NONE").config(
        "spark.rapids.tpu.sql.compile.cacheDir", cache_dir).config(
        "spark.rapids.tpu.sql.compile.prewarm.topN",
        str(top_n)).getOrCreate()
    submitted = compile_pool.prewarm(session.conf)
    drained = compile_pool.drain(timeout_s=timeout_s)
    stats = compile_pool.stats()
    out = {
        "cacheDir": cache_dir,
        "submitted": submitted,
        "drained": bool(drained),
        "prewarmBuilt": stats.get("prewarmBuilt", 0),
        "failed": stats.get("failed", 0),
        "ok": bool(drained) and submitted >= 0 and
              stats.get("failed", 0) == 0 and
              stats.get("prewarmBuilt", 0) >= submitted > 0,
    }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compile the hottest recorded fused-stage "
                    "signatures into a compile-cache dir before "
                    "traffic arrives")
    ap.add_argument("--cache-dir", required=True,
                    help="persistent compile cache directory "
                         "(spark.rapids.tpu.sql.compile.cacheDir) "
                         "holding a prior run's prewarm corpus")
    ap.add_argument("--top-n", type=int, default=32,
                    help="hottest signatures to compile "
                         "(compile.prewarm.topN)")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="seconds to wait for the background builds")
    args = ap.parse_args(argv)
    out = run_prewarm(args.cache_dir, top_n=args.top_n,
                      timeout_s=args.timeout)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
