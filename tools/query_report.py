"""Render a query-log JSONL into per-query human digests.

Usage::

    python -m tools.query_report path/to/query_log-*.jsonl [--top 5]

Reads one or more structured query-log files (conf
``spark.rapids.tpu.sql.telemetry.queryLog.dir``, service/query_log.py)
and prints, per query id: the headline (wall, rows, cache verdicts), the
top operators by time, the skewest exchange, the adaptive-execution
decisions (docs/aqe.md), the worst estimate-vs-actual drift, and
retries/faults — the "what happened in this CI artifact" answer without
opening JSON by hand. Records from
multiple workers sharing a query id (a distributed run) merge into one
digest with per-worker stage lines.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def load_records(paths: List[str]) -> List[dict]:
    out: List[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue            # torn tail line: skip, not fatal
    return out


def _skewest(records: List[dict]) -> dict:
    best = None
    for rec in records:
        for st in rec.get("stageStats", ()) or ():
            if best is None or st.get("skew", 0) > best.get("skew", 0):
                best = st
    return best or {}


def _worst_drift(records: List[dict]) -> dict:
    best = None
    best_mag = 0.0
    for rec in records:
        worst = (rec.get("drift") or {}).get("worst")
        if not worst:
            continue
        r = float(worst.get("ratio", 1.0)) or 1e-9
        mag = max(r, 1.0 / r)
        if mag > best_mag:
            best, best_mag = worst, mag
    return best or {}


def _aqe_rules(records: List[dict]) -> Dict[str, dict]:
    """rule -> merged applied/declined counts across worker records
    (the ``aqe`` record field, plan/aqe.py)."""
    out: Dict[str, dict] = {}
    for rec in records:
        for rule, counts in ((rec.get("aqe") or {}).get("rules")
                             or {}).items():
            e = out.setdefault(rule, {"applied": 0, "declined": 0})
            e["applied"] += int(counts.get("applied", 0) or 0)
            e["declined"] += int(counts.get("declined", 0) or 0)
    return out


def digest(query_id: str, records: List[dict], top: int = 5) -> str:
    """One query's digest text from its (possibly multi-worker)
    records."""
    lines: List[str] = []
    head = records[0]
    wall = max(float(r.get("wallS", 0) or 0) for r in records)
    rows = sum(int(r.get("rows", 0) or 0) for r in records)
    retries = sum(int(r.get("stageRetries", 0) or 0) for r in records)
    faults = sum(int(r.get("faultsFired", 0) or 0) for r in records)
    tenant = next((r.get("tenant") for r in records if r.get("tenant")),
                  None)
    lines.append(f"query {query_id}  "
                 + (f"tenant={tenant}  " if tenant else "")
                 + f"({len(records)} worker record(s))")
    lines.append(
        f"  wallS={wall} rows={rows} "
        f"planCache={head.get('planCache')} "
        f"resultCache={head.get('resultCache')} "
        f"params={head.get('params', 0)}")
    # cold/warm breakdown (docs/compile.md §5): compileS is the wall the
    # query thread spent blocked on synchronous stage builds; executeS
    # is the rest. A prewarmed/async-served query shows compileS=0 —
    # the rollup's prewarm hit rate counts exactly those. firstRowS <
    # wallS marks a streaming (collect_iter) execution.
    compile_s = max(float(r.get("compileS", 0) or 0) for r in records)
    first_row = max(float(r.get("firstRowS", 0) or 0) for r in records)
    if compile_s or first_row:
        lines.append(
            f"  compileS={round(compile_s, 4)} "
            f"executeS={round(max(0.0, wall - compile_s), 4)} "
            f"firstRowS={round(first_row, 4)}")
    if retries or faults:
        lines.append(f"  retries: stage={retries} "
                     f"fetch={sum(int(r.get('fetchRetries', 0) or 0) for r in records)} "
                     f"faultsFired={faults}")
    # top operators by time, merged across workers
    ops: Dict[str, dict] = {}
    for rec in records:
        for op in rec.get("operators", ()) or ():
            e = ops.setdefault(op["operator"],
                               {"opTimeS": 0.0, "rows": 0})
            e["opTimeS"] += float(op.get("opTimeS", 0) or 0)
            e["rows"] += int(op.get("rows", 0) or 0)
    ranked = sorted(ops.items(), key=lambda kv: -kv[1]["opTimeS"])[:top]
    if ranked:
        lines.append("  top operators by time:")
        for name, e in ranked:
            lines.append(f"    {name}: {round(e['opTimeS'], 4)}s "
                         f"rows={e['rows']}")
    sk = _skewest(records)
    if sk:
        lines.append(
            f"  skewest exchange: stage {sk.get('stageId')} "
            f"[{sk.get('plane')}] skew={sk.get('skew')} "
            f"p50Bytes={int(sk.get('p50Bytes', 0))} "
            f"maxBytes={sk.get('maxBytes')} "
            f"partitions={sk.get('partitions')}")
    aqe = _aqe_rules(records)
    if aqe:
        lines.append("  aqe decisions: " + "  ".join(
            f"{rule}={e['applied']}"
            + (f"(+{e['declined']} declined)" if e["declined"] else "")
            for rule, e in sorted(aqe.items())))
        applied = [d for rec in records
                   for d in ((rec.get("aqe") or {}).get("decisions")
                             or ()) if d.get("applied")]
        for d in applied[:top]:
            lines.append(f"    {d.get('rule')} @ {d.get('operator')}: "
                         f"{d.get('before')} -> {d.get('after')} "
                         f"({d.get('reason')})")
    wd = _worst_drift(records)
    if wd:
        lines.append(
            f"  worst drift: {wd.get('operator')} "
            f"est={wd.get('estRows')} actual={wd.get('actualRows')} "
            f"ratio={wd.get('ratio')}x")
    flagged = sum((r.get("drift") or {}).get("flagged", 0)
                  for r in records)
    if flagged:
        lines.append(f"  drift flags past threshold: {flagged}")
    hbm = max((int(r.get("hbmPeakBytes", 0) or 0) for r in records),
              default=0)
    if hbm:
        op = next((r.get("hbmPeakOperator") for r in records
                   if r.get("hbmPeakOperator")), None)
        lines.append(f"  hbm peak: {hbm} bytes"
                     + (f" ({op})" if op else ""))
    # buffer-lifecycle verdict (analysis/ledger.py): the leak line only
    # appears when some worker actually leaked — a clean corpus stays
    # clean-looking
    leaked = sum(int(r.get("leakedBuffers", 0) or 0) for r in records)
    if leaked:
        peak = max((int(r.get("peakDeviceBytes", 0) or 0)
                    for r in records), default=0)
        lines.append(f"  LEAKED BUFFERS: {leaked} "
                     f"(peakDeviceBytes={peak}) — see the buffer-leak "
                     "flight events for mint sites")
    return "\n".join(lines)


def tenant_rollup(records: List[dict]) -> str:
    """Per-tenant summary across every record carrying a tenant id
    (service multi-tenancy, docs/service.md): query count, wall seconds,
    rows, retries, and preempted/cancelled lifecycle counts — empty
    string when no record is tenant-tagged.
    Multi-worker records sharing a query id count as ONE query (wall =
    the slowest worker, the digest() rule; rows/retries sum across
    workers, each worker returns/retries its own partitions)."""
    by_query: Dict[tuple, List[dict]] = {}
    for rec in records:
        t = rec.get("tenant")
        if not t:
            continue
        by_query.setdefault((t, str(rec.get("queryId"))),
                            []).append(rec)
    by_tenant: Dict[str, dict] = {}
    for (t, _qid), recs in by_query.items():
        e = by_tenant.setdefault(t, {"queries": 0, "wallS": 0.0,
                                     "rows": 0, "retries": 0,
                                     "compileS": 0.0, "warm": 0,
                                     "preempted": 0, "cancelled": 0})
        e["queries"] += 1
        e["wallS"] += max(float(r.get("wallS", 0) or 0) for r in recs)
        e["rows"] += sum(int(r.get("rows", 0) or 0) for r in recs)
        e["retries"] += sum(int(r.get("stageRetries", 0) or 0)
                            for r in recs)
        comp = max(float(r.get("compileS", 0) or 0) for r in recs)
        e["compileS"] += comp
        if comp == 0.0:
            # served with zero synchronous build wall: a prewarm/async/
            # cache hit — the fraction of these is the prewarm hit rate
            e["warm"] += 1
        # lifecycle transitions (exec/lifecycle.py, docs/service.md §4):
        # a query counts as preempted/cancelled ONCE no matter how many
        # suspend cycles or worker records it went through
        states = {tr.get("state")
                  for r in recs for tr in (r.get("lifecycle") or ())}
        if "suspended" in states:
            e["preempted"] += 1
        if "cancelled" in states:
            e["cancelled"] += 1
    if not by_tenant:
        return ""
    lines = ["per-tenant summary:"]
    for t, e in sorted(by_tenant.items()):
        hit = e["warm"] / e["queries"] if e["queries"] else 0.0
        lines.append(
            f"  {t}: queries={e['queries']} "
            f"wallS={round(e['wallS'], 4)} rows={e['rows']} "
            f"compileS={round(e['compileS'], 4)} "
            f"prewarmHitRate={round(hit, 3)}"
            + (f" stageRetries={e['retries']}" if e["retries"] else "")
            + (f" preempted={e['preempted']}" if e["preempted"] else "")
            + (f" cancelled={e['cancelled']}" if e["cancelled"] else ""))
    return "\n".join(lines)


def render(paths: List[str], top: int = 5) -> str:
    records = load_records(paths)
    if not records:
        return "no query-log records found"
    by_query: Dict[str, List[dict]] = {}
    order: List[str] = []
    for rec in records:
        qid = str(rec.get("queryId"))
        if qid not in by_query:
            order.append(qid)
        by_query.setdefault(qid, []).append(rec)
    out = "\n\n".join(digest(q, by_query[q], top=top) for q in order)
    roll = tenant_rollup(records)
    if roll:
        out += "\n\n" + roll
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render query-log JSONL into per-query digests")
    ap.add_argument("paths", nargs="+", help="query_log-*.jsonl files")
    ap.add_argument("--top", type=int, default=5,
                    help="operators per query in the time ranking")
    args = ap.parse_args(argv)
    print(render(args.paths, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
