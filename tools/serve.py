"""CLI front door for the multi-tenant query service.

Two modes (docs/service.md §6):

One-shot SQL through the service (admission + tenant attribution on a
single query)::

    python -m tools.serve --sf 0.001 --tenant gold \
        --sql "SELECT count(*) AS n FROM lineitem"

Mixed-tenant demo traffic (the benchmarks/replay.py engine, without the
history stamp) printing the per-tenant service stats::

    python -m tools.serve --sf 0.001 --streams 4 --iters 4
    python -m tools.serve --faults "fetch.fail;task.poison"

Tenants are declared ``name:key=value:...`` with keys ``priority``,
``slots``, ``depth`` (max queue depth), ``budget`` (device bytes, byte
suffixes allowed) and ``weight`` (the weighted-fair share under
``--policy wfq``)::

    --tenants "gold:priority=10:slots=2:budget=1g:weight=3,bronze:priority=0"

Query lifecycle control (docs/service.md §4): ``--cancel-after`` /
``--suspend-after`` / ``--resume-after`` arm timers that drive the
service's ``cancel(query_id)`` / ``suspend(query_id)`` /
``resume(query_id)`` surface against the live query — a one-process
demonstration of cooperative cancellation and suspend/resume::

    python -m tools.serve --sql "SELECT ..." --suspend-after 0.2 \
        --resume-after 1.0
"""

from __future__ import annotations

import argparse
import json
import sys


def parse_tenant_specs(text: str):
    """``name:key=value:...`` comma-separated -> [TenantSpec]."""
    from spark_rapids_tpu.config import parse_bytes
    from spark_rapids_tpu.service.tenants import TenantSpec
    specs = []
    for raw in (text or "").split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        name, kw = parts[0], {}
        for p in parts[1:]:
            if "=" not in p:
                raise ValueError(
                    f"bad tenant field {p!r} in {raw!r} "
                    "(expect key=value)")
            k, v = p.split("=", 1)
            if k == "priority":
                kw["priority"] = int(v)
            elif k == "slots":
                kw["slots"] = int(v)
            elif k == "depth":
                kw["max_queue_depth"] = int(v)
            elif k == "budget":
                kw["memory_budget_bytes"] = parse_bytes(v)
            elif k == "weight":
                kw["weight"] = float(v)
            else:
                raise ValueError(f"unknown tenant field {k!r} in {raw!r}")
        specs.append(TenantSpec(name, **kw))
    return specs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run queries through the multi-tenant query service")
    ap.add_argument("--sf", type=float, default=0.001,
                    help="TPC-H scale factor of the generated tables")
    ap.add_argument("--tenants",
                    default="gold:priority=10:slots=2,"
                            "bronze:priority=0:slots=1",
                    help="tenant specs: name:key=value:... (keys: "
                         "priority, slots, depth, budget)")
    ap.add_argument("--tenant", default="gold",
                    help="tenant for --sql submissions")
    ap.add_argument("--sql", action="append", default=[],
                    help="SQL to run through the service (repeatable; "
                         "TPC-H tables are registered as views)")
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-query deadline seconds for --sql")
    ap.add_argument("--streams", type=int, default=4,
                    help="demo-traffic concurrent streams (no --sql)")
    ap.add_argument("--iters", type=int, default=4,
                    help="demo-traffic queries per stream")
    ap.add_argument("--faults", default=None,
                    help="chaos spec for the demo traffic")
    ap.add_argument("--policy", choices=("priority", "wfq"), default=None,
                    help="scheduler policy (service.scheduler.policy)")
    ap.add_argument("--cancel-after", type=float, default=None,
                    help="seconds after which the live query is "
                         "cancelled via QueryService.cancel(query_id)")
    ap.add_argument("--suspend-after", type=float, default=None,
                    help="seconds after which the live query is parked "
                         "via QueryService.suspend(query_id); pair with "
                         "--resume-after or the ticket waits until close")
    ap.add_argument("--resume-after", type=float, default=None,
                    help="seconds after which suspended queries are "
                         "re-admitted via QueryService.resume(query_id)")
    args = ap.parse_args(argv)

    if not args.sql:
        # demo traffic: the replay engine without the history stamp
        from benchmarks.replay import run_replay
        line = run_replay(sf=args.sf, streams=args.streams,
                          queries_per_stream=args.iters,
                          faults=args.faults, stamp=False)
        print(json.dumps(line, default=str))
        return 0 if line.get("replay_ok") else 1

    from benchmarks import datagen
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.service.server import QueryService
    conf = {"spark.rapids.tpu.sql.explain": "NONE"}
    if args.policy:
        conf["spark.rapids.tpu.sql.service.scheduler.policy"] = args.policy
    session = TpuSession.builder.config(conf).getOrCreate()
    datagen.register_tables(session, args.sf)
    svc = QueryService(session, tenants=parse_tenant_specs(args.tenants))

    def _lifecycle_timer(delay, op):
        """Fire ``op`` against the live/suspended query ids after
        ``delay`` seconds (the one-process lifecycle demo surface)."""
        import threading
        import time as _time
        from spark_rapids_tpu.exec import lifecycle

        def fire():
            _time.sleep(delay)
            if op == "resume":
                ids = svc.suspended_queries()
            else:
                ids = lifecycle.live_queries()
            for qid in ids:
                try:
                    done = getattr(svc, op)(qid)
                except Exception as e:
                    done = f"{type(e).__name__}: {e}"
                print(json.dumps({"lifecycle": op, "queryId": qid,
                                  "result": done}, default=str))

        t = threading.Thread(target=fire, daemon=True,
                             name=f"serve-{op}-timer")
        t.start()
        return t

    timers = []
    if args.cancel_after is not None:
        timers.append(_lifecycle_timer(args.cancel_after, "cancel"))
    if args.suspend_after is not None:
        timers.append(_lifecycle_timer(args.suspend_after, "suspend"))
    if args.resume_after is not None:
        timers.append(_lifecycle_timer(args.resume_after, "resume"))
    rc = 0
    try:
        for sql in args.sql:
            ticket = svc.submit(args.tenant, sql,
                                deadline_s=args.deadline)
            try:
                batch = ticket.result(timeout=600)
                print(json.dumps({
                    "tenant": ticket.tenant, "sql": sql,
                    "queryId": ticket.query_id,
                    "queueWaitS": round(ticket.queue_wait_s(), 4),
                    "latencyS": round(ticket.latency_s(), 4),
                    "rows": batch.rows()}, default=str))
            except Exception as e:
                rc = 1
                print(json.dumps({
                    "tenant": ticket.tenant, "sql": sql,
                    "error": f"{type(e).__name__}: {e}"}, default=str))
        print(json.dumps({"service": svc.stats()}, default=str))
    finally:
        svc.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
